"""Benchmark: Llama pretrain step on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip on a Llama block-scaled pretrain step (bf16,
flash attention, remat, AdamW w/ fp32 master) + estimated MFU vs chip
peak. vs_baseline = MFU / 0.40 (BASELINE.json north-star: ≥40% MFU).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# chip peak bf16 FLOP/s (dense) by generation
PEAK_FLOPS = {
    "v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12,
    "cpu": 1e12,
}


def _data_rng():
    """Per-process random data seed (PT_BENCH_DATA_SEED pins it): the
    axon serving terminal memoizes (executable, inputs) → output across
    processes, so fixed-seed reruns of an already-benched config return
    cached results without executing (observed 2026-08-01: impossible
    'MFU 2.43' / step_time 0.21s on a config that honestly measures
    1.38s). Fresh data defeats the memo while params stay seed-pinned
    for comparability. Shared by bench.py and bench_models.py."""
    s = os.environ.get("PT_BENCH_DATA_SEED")
    seed = int(s) if s is not None else int.from_bytes(os.urandom(4), "little")
    return np.random.RandomState(seed)


def _tpu_alive():
    """Probe device init in a child so a wedged TPU tunnel can't hang the
    bench. Retries with growing timeouts and logs the child's stderr —
    a silent CPU fallback hides the only number that matters.

    Fast path (VERDICT r4 weak #3: the probe ladder burned 720s in a
    driver-invoked artifact): tools/tpu_watch.sh records every probe
    verdict in .tpu_state.json; a recent DOWN from the watcher
    short-circuits the ladder entirely. A recent UP still re-probes
    (cheap when alive) since windows die faster than the state ages."""
    import subprocess
    state = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".tpu_state.json")
    try:
        with open(state) as f:
            st = json.load(f)
        if not st["up"] and time.time() - st["ts"] < 600:
            print("# TPU watcher saw tunnel down "
                  f"{int(time.time() - st['ts'])}s ago; skipping probe",
                  file=sys.stderr)
            return False
    except (OSError, ValueError, KeyError):
        pass
    for attempt, timeout in enumerate((120, 240, 360), 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); print(d[0].platform)"],
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"# TPU probe attempt {attempt} timed out after {timeout}s",
                  file=sys.stderr)
            continue
        if r.returncode == 0:
            return True
        print(f"# TPU probe attempt {attempt} rc={r.returncode}; stderr tail:",
              file=sys.stderr)
        print("\n".join(r.stderr.strip().splitlines()[-10:]), file=sys.stderr)
        time.sleep(10)
    return False


def _maybe_validate_kernels():
    """A live driver run must never produce a bench number while the
    pallas kernels sit unvalidated (VERDICT r2 item 1): run the on-chip
    kernel validation suite (writes TPU_VALIDATION.json) before benching,
    unless a fresh result already exists or PT_BENCH_SKIP_VALIDATE=1
    (set by tools/tpu_capture.sh, which runs validation itself first)."""
    if os.environ.get("PT_BENCH_SKIP_VALIDATE") == "1":
        return
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "TPU_VALIDATION.json")
    try:
        # skip only when the existing result is BOTH fresh and passing —
        # a fresh failure must not suppress re-validation
        if time.time() - os.path.getmtime(path) < 6 * 3600:
            with open(path) as f:
                if json.load(f).get("ok"):
                    return
    except (OSError, json.JSONDecodeError):
        pass
    import subprocess
    print("# validating pallas kernels on-chip (-> TPU_VALIDATION.json)",
          file=sys.stderr)
    try:
        # stdout -> stderr: the validator prints PASS/FAIL lines and its
        # own JSON line, which must not pollute bench.py's single-JSON-
        # line stdout contract with the driver
        r = subprocess.run(
            [sys.executable,
             os.path.join(here, "tools", "validate_tpu_kernels.py")],
            stdout=sys.stderr,
            timeout=int(os.environ.get("PT_VALIDATE_TIMEOUT", "900")))
        if r.returncode != 0:
            print(f"# kernel validation FAILED (rc={r.returncode}) — "
                  "TPU_VALIDATION.json records which kernels; benching "
                  "anyway so a number still exists", file=sys.stderr)
    except (subprocess.TimeoutExpired, OSError) as e:
        print(f"# kernel validation did not finish ({e}); benching anyway",
              file=sys.stderr)


def _tuned_defaults():
    """Winning config from tools/autotune.py, if one was ever captured."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, "TUNED.json")) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if data.get("smoke"):
        # a smoke-mode search wrote here (PT_TUNE_OUT override or a
        # copied TUNED.smoke.json) — fake numbers must not become the
        # on-chip defaults
        return {}
    return data.get("best", {})


def _tpu_history():
    """(most recent, best-strict-MFU) TPU entries from
    BENCH_HISTORY.jsonl — after an autotune sweep the most RECENT entry
    can be a mediocre trial config, so the best entry must ride along
    or a tunnel-down driver run understates the real headline."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "BENCH_HISTORY.jsonl")
    last = best = None

    def _pick(e):
        out = {k: e[k] for k in
               ("metric", "value", "unit", "vs_baseline", "ts", "batch",
                "seq", "remat", "fused_ce", "n_micro", "docs") if k in e}
        out["mfu"] = e["extra"].get("mfu")
        out["mfu_legacy"] = e["extra"].get("mfu_legacy")
        return out

    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # llama-headline entries only (they carry top-level
                # batch/seq); bench_models.py rows must not masquerade as
                # the pretrain datapoint
                if e.get("extra", {}).get("backend") in (None, "cpu") \
                        or "batch" not in e or "seq" not in e:
                    continue
                if e.get("extra", {}).get("invalid"):
                    # annotated-after-the-fact bogus measurement (e.g.
                    # the 2026-08-01 terminal-memoization phantoms) —
                    # never serve as last or best
                    continue
                last = _pick(e)
                # pre-r3 entries recorded LEGACY mfu under the "mfu"
                # key (no mfu_legacy field) — comparing that against
                # strict values would crown a stale legacy number — and
                # a pallas_fallback run executed the XLA path, which
                # must never be presented as the pallas headline: both
                # sit out the "best" competition
                if e.get("extra", {}).get("mfu") is not None and \
                        e["extra"].get("mfu_legacy") is not None and \
                        not e["extra"].get("pallas_fallback") and \
                        (best is None or e["extra"]["mfu"] > best["mfu"]):
                    best = _pick(e)
    except OSError:
        return None, None
    return last, best


def main():
    import jax
    guarded_child = os.environ.get("_PT_BENCH_GUARDED") == "1"
    if os.environ.get("PT_BENCH_CPU") == "1" or \
            (not guarded_child and not _tpu_alive()):
        print("# TPU unreachable; benching CPU smoke fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    elif not guarded_child:
        _maybe_validate_kernels()
        # the probe passing does not guarantee compile/execute will —
        # a half-wedged tunnel can hang (or die) AFTER device init, which
        # would leave the driver with no output line at all. Run the real
        # bench in a guarded child; on timeout OR crash fall back to the
        # CPU smoke (which still surfaces last/best_tpu_measured).
        import subprocess
        env = dict(os.environ, _PT_BENCH_GUARDED="1")
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, timeout=int(os.environ.get(
                                   "PT_BENCH_TIMEOUT", "1500")))
            if r.returncode == 0:
                sys.exit(0)
            print(f"# TPU bench child died rc={r.returncode}; "
                  "CPU smoke fallback", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print("# TPU bench hung past the watchdog; CPU smoke fallback",
                  file=sys.stderr)
        env = dict(os.environ, PT_BENCH_CPU="1")
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env).returncode)
    import jax.numpy as jnp
    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") if on_tpu else "cpu"
    peak = PEAK_FLOPS.get(gen, 197e12)

    # apply tuned flash block sizes BEFORE paddle_tpu imports: the kernel
    # module reads PT_FLASH_BLOCK_Q/K at import time
    tuned = _tuned_defaults() if on_tpu else {}
    for var, key in (("PT_FLASH_BLOCK_Q", "block_q"),
                     ("PT_FLASH_BLOCK_K", "block_k")):
        if var not in os.environ and key in tuned:
            os.environ[var] = str(tuned[key])

    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_spmd as M

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        # defaults: TUNED.json (autotuner winner) when present, else the
        # best hand-measured config on v5e (r2 sweep: batch 16 →
        # 23.5k tok/s; batch 8 worse; remat=false OOMs)
        batch = int(os.environ.get("PT_BENCH_BATCH", tuned.get("batch", 16)))
        seq = int(os.environ.get("PT_BENCH_SEQ", tuned.get("seq", 2048)))
        iters, dtype = 10, jnp.bfloat16
        remat = os.environ.get("PT_BENCH_REMAT",
                               str(tuned.get("remat", "true")).lower())
        remat = {"true": True, "false": False}.get(remat, remat)
    else:  # CPU smoke fallback
        cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=4,
                               kv_heads=2, ffn=256)
        batch, seq, iters, dtype = 2, 128, 3, jnp.float32
        remat = True

    n_micro = int(os.environ.get("PT_BENCH_NMICRO",
                                 str(tuned.get("n_micro", 0)))) or None
    # fused linear+CE head (no (B,S,V) logits materialization) — the
    # biggest single-chip MFU lever at vocab 32000; swept by autotune
    fused_ce = os.environ.get(
        "PT_FUSED_CE", "1" if tuned.get("fused_ce") else "0") == "1"
    if n_micro and batch % n_micro:
        # an indivisible n_micro would trip the grad-accum assert during
        # trace and get swallowed by the pallas-fallback except below,
        # silently benching a config other than the labeled one
        print(f"# n_micro={n_micro} does not divide batch={batch}; "
              "disabling grad accumulation", file=sys.stderr)
        n_micro = None
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    params = M.init_params(cfg, seed=0, dtype=dtype)
    opt = M.init_opt_state(params)
    step = M.make_train_step(cfg, mesh, n_micro=n_micro, remat=remat, lr=3e-4,
                             fused_ce=fused_ce)

    rng = _data_rng()  # random data per process: see _data_rng docstring
    x = rng.randint(0, cfg.vocab_size, (batch, seq))
    y = rng.randint(0, cfg.vocab_size, (batch, seq))
    # PT_BENCH_DOCS=N: packed-document pretrain — N equal documents per
    # row, cross-document attention blocked by the flashmask kernel
    # (block-skip turns the saved attention into real tok/s)
    docs = int(os.environ.get("PT_BENCH_DOCS", "0"))
    if docs > 0:
        assert seq % docs == 0, f"seq {seq} not divisible by docs {docs}"
        doc_ids = np.repeat(np.arange(docs),
                            seq // docs)[None].repeat(batch, 0)
        data = (x, y, doc_ids)
    else:
        data = (x, y)

    # compile + warmup; if the pallas kernel is rejected on this chip
    # generation, fall back to the XLA attention path rather than dying —
    # but RECORD the fallback so autotune/perf-guard never score the XLA
    # number as if it were this pallas block config
    pallas_fallback = False
    try:
        params, opt, loss = step(params, opt, jnp.asarray(0), data)
        jax.block_until_ready(loss)
    except Exception as e:
        # HBM OOM is a CONFIG failure, not a pallas failure: retrying
        # with the XLA attention path would recompile, OOM again, and
        # burn a tunnel window for nothing. Die fast so autotune marks
        # the trial and moves on. Scoped-VMEM / Mosaic exhaustion is
        # different — that IS a pallas block-config failure and the XLA
        # fallback below would succeed, so let it through.
        msg = str(e)
        low = msg.lower()
        oom = "resource_exhausted" in low or "out of memory" in low
        vmem = "vmem" in low or "mosaic" in low or "scoped" in low
        if oom and not vmem:
            print(f"# config OOM ({type(e).__name__}): "
                  + msg.splitlines()[0][:200], file=sys.stderr)
            sys.exit(7)
        if os.environ.get("PT_BENCH_NO_FALLBACK") == "1":
            # autotune trials: a pallas-rejected number would be
            # discarded as pallas_fallback anyway — skip the expensive
            # XLA recompile and fail the trial immediately
            print(f"# pallas path failed ({type(e).__name__}) and "
                  "PT_BENCH_NO_FALLBACK=1; failing trial without XLA "
                  "retry: " + msg.splitlines()[0][:200], file=sys.stderr)
            sys.exit(8)
        print(f"# pallas path failed ({type(e).__name__}); "
              "retrying with PT_DISABLE_PALLAS=1", file=sys.stderr)
        pallas_fallback = True
        os.environ["PT_DISABLE_PALLAS"] = "1"
        params = M.init_params(cfg, seed=0, dtype=dtype)
        opt = M.init_opt_state(params)
        step = M.make_train_step(cfg, mesh, n_micro=n_micro, remat=remat,
                                 lr=3e-4, fused_ce=fused_ce)
        params, opt, loss = step(params, opt, jnp.asarray(0), data)
        jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(iters):
        params, opt, loss = step(params, opt, jnp.asarray(i + 1), data)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch * seq
    tok_per_sec = tokens_per_step / dt

    # Model FLOPs/token — STRICT convention (VERDICT r2 item 2):
    #   * 6*N counts matmul parameters only. The input-embedding lookup
    #     is a gather, not a matmul → EXCLUDED. The lm_head projection
    #     is a real matmul → kept (one V*H term, not two).
    #   * attention is charged at the FULL (non-causal) 12*L*H*S
    #     fwd+bwd even though the kernel is causal, so numbers stay
    #     comparable with the reference's convention.
    # mfu_legacy (both V*H terms) is also printed: it is the convention
    # rounds 1-2 reported, kept for cross-round comparability.
    H, L, F, V = (cfg.hidden_size, cfg.num_hidden_layers,
                  cfg.intermediate_size, cfg.vocab_size)
    kv = cfg.num_key_value_heads * (H // cfg.num_attention_heads)
    n_layers = L * (2 * H * H + 2 * H * kv + 3 * H * F)
    attn = 12 * L * H * seq
    flops_strict = 6 * (n_layers + V * H) + attn
    flops_legacy = 6 * (n_layers + 2 * V * H) + attn
    mfu = flops_strict * tok_per_sec / peak
    mfu_legacy = flops_legacy * tok_per_sec / peak

    attn_label = f"flashmask-{docs}doc" if docs > 0 else "flash-attn"
    remat_label = {True: "remat", False: "no-remat"}.get(
        remat, f"remat-{remat}")
    result = {
        "metric": f"llama-{f'{seq}x{batch}' if on_tpu else 'tiny'} pretrain "
                  f"tokens/sec/chip ({gen}, bf16, {attn_label}, "
                  f"{remat_label})",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "mfu_legacy": round(mfu_legacy, 4),
                  "flops_convention": "6N excl. embedding gather (lm_head "
                                      "kept); attention full 12LHS on a "
                                      "causal kernel",
                  "loss": float(loss), "backend": backend,
                  "fused_ce": fused_ce,
                  "pallas_fallback": pallas_fallback},
    }
    if not on_tpu:
        # the chip tunnel comes and goes; if it is down right now, surface
        # the most recent AND the best REAL TPU measurements (clearly
        # labeled with timestamps) alongside the smoke number instead of
        # erasing them
        last, best = _tpu_history()
        if last is not None:
            result["extra"]["last_tpu_measured"] = last
        if best is not None:
            result["extra"]["best_tpu_measured"] = best
    print(json.dumps(result))
    # perf-regression history: tests/test_perf_guard.py compares the last
    # two same-backend/same-config entries
    try:
        # history entry: shallow-copy extra WITHOUT the nested
        # last/best_tpu_measured report fields (they would re-embed
        # previous TPU entries into every CPU line)
        extra = {k: v for k, v in result["extra"].items()
                 if k not in ("last_tpu_measured", "best_tpu_measured")}
        hist = dict(result, extra=extra, ts=time.time(), batch=batch,
                    seq=seq, remat=str(remat), n_micro=n_micro,
                    docs=docs or None, fused_ce=fused_ce,
                    block_q=os.environ.get("PT_FLASH_BLOCK_Q"),
                    block_k=os.environ.get("PT_FLASH_BLOCK_K"))
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BENCH_HISTORY.jsonl"), "a") as f:
            f.write(json.dumps(hist) + "\n")
    except OSError:
        pass


if __name__ == "__main__":
    main()
