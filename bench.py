"""Benchmark: Llama pretrain step on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip on a Llama block-scaled pretrain step (bf16,
flash attention, remat, AdamW w/ fp32 master) + estimated MFU vs chip
peak. vs_baseline = MFU / 0.40 (BASELINE.json north-star: ≥40% MFU).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# chip peak bf16 FLOP/s (dense) by generation
PEAK_FLOPS = {
    "v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12,
    "cpu": 1e12,
}


def _tpu_alive():
    """Probe device init in a child so a wedged TPU tunnel can't hang the
    bench. Retries with growing timeouts and logs the child's stderr —
    a silent CPU fallback hides the only number that matters."""
    import subprocess
    for attempt, timeout in enumerate((120, 240, 360), 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); print(d[0].platform)"],
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"# TPU probe attempt {attempt} timed out after {timeout}s",
                  file=sys.stderr)
            continue
        if r.returncode == 0:
            return True
        print(f"# TPU probe attempt {attempt} rc={r.returncode}; stderr tail:",
              file=sys.stderr)
        print("\n".join(r.stderr.strip().splitlines()[-10:]), file=sys.stderr)
        time.sleep(10)
    return False


def _last_tpu_history():
    """Most recent TPU entry from BENCH_HISTORY.jsonl, or None."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "BENCH_HISTORY.jsonl")
    last = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # llama-headline entries only (they carry top-level
                # batch/seq); bench_models.py rows must not masquerade as
                # the pretrain datapoint
                if e.get("extra", {}).get("backend") not in (None, "cpu") \
                        and "batch" in e and "seq" in e:
                    last = {k: e[k] for k in
                            ("metric", "value", "unit", "vs_baseline",
                             "ts", "batch", "seq", "remat") if k in e}
                    last["mfu"] = e["extra"].get("mfu")
    except OSError:
        return None
    return last


def main():
    import jax
    guarded_child = os.environ.get("_PT_BENCH_GUARDED") == "1"
    if os.environ.get("PT_BENCH_CPU") == "1" or \
            (not guarded_child and not _tpu_alive()):
        print("# TPU unreachable; benching CPU smoke fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    elif not guarded_child:
        # the probe passing does not guarantee compile/execute will —
        # a half-wedged tunnel can hang (or die) AFTER device init, which
        # would leave the driver with no output line at all. Run the real
        # bench in a guarded child; on timeout OR crash fall back to the
        # CPU smoke (which still surfaces last_tpu_measured).
        import subprocess
        env = dict(os.environ, _PT_BENCH_GUARDED="1")
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, timeout=int(os.environ.get(
                                   "PT_BENCH_TIMEOUT", "1500")))
            if r.returncode == 0:
                sys.exit(0)
            print(f"# TPU bench child died rc={r.returncode}; "
                  "CPU smoke fallback", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print("# TPU bench hung past the watchdog; CPU smoke fallback",
                  file=sys.stderr)
        env = dict(os.environ, PT_BENCH_CPU="1")
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env).returncode)
    import jax.numpy as jnp
    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") if on_tpu else "cpu"
    peak = PEAK_FLOPS.get(gen, 197e12)

    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_spmd as M

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        # defaults = best measured config on v5e (r2 sweep: batch 16 →
        # 23.5k tok/s, 40.7% MFU; batch 8 → 26.4%; remat=false OOMs)
        batch = int(os.environ.get("PT_BENCH_BATCH", "16"))
        seq = int(os.environ.get("PT_BENCH_SEQ", "2048"))
        iters, dtype = 10, jnp.bfloat16
        remat = os.environ.get("PT_BENCH_REMAT", "true")
        remat = {"true": True, "false": False}.get(remat, remat)
    else:  # CPU smoke fallback
        cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=4,
                               kv_heads=2, ffn=256)
        batch, seq, iters, dtype = 2, 128, 3, jnp.float32
        remat = True

    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    params = M.init_params(cfg, seed=0, dtype=dtype)
    opt = M.init_opt_state(params)
    step = M.make_train_step(cfg, mesh, n_micro=None, remat=remat, lr=3e-4)

    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, (batch, seq))
    y = rng.randint(0, cfg.vocab_size, (batch, seq))

    # compile + warmup; if the pallas kernel is rejected on this chip
    # generation, fall back to the XLA attention path rather than dying
    try:
        params, opt, loss = step(params, opt, jnp.asarray(0), (x, y))
        jax.block_until_ready(loss)
    except Exception as e:
        print(f"# pallas path failed ({type(e).__name__}); "
              "retrying with PT_DISABLE_PALLAS=1", file=sys.stderr)
        os.environ["PT_DISABLE_PALLAS"] = "1"
        params = M.init_params(cfg, seed=0, dtype=dtype)
        opt = M.init_opt_state(params)
        step = M.make_train_step(cfg, mesh, n_micro=None, remat=remat, lr=3e-4)
        params, opt, loss = step(params, opt, jnp.asarray(0), (x, y))
        jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(iters):
        params, opt, loss = step(params, opt, jnp.asarray(i + 1), (x, y))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch * seq
    tok_per_sec = tokens_per_step / dt

    # model FLOPs per token: 6*N_matmul + attention 12*L*H_dim*S terms
    H, L, F, V = (cfg.hidden_size, cfg.num_hidden_layers,
                  cfg.intermediate_size, cfg.vocab_size)
    kv = cfg.num_key_value_heads * (H // cfg.num_attention_heads)
    n_matmul = L * (2 * H * H + 2 * H * kv + 3 * H * F) + 2 * V * H
    flops_per_token = 6 * n_matmul + 12 * L * H * seq  # fwd+bwd incl. attn
    mfu = flops_per_token * tok_per_sec / peak

    result = {
        "metric": f"llama-{f'{seq}x{batch}' if on_tpu else 'tiny'} pretrain "
                  f"tokens/sec/chip ({gen}, bf16, flash-attn, remat)",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_time_s": round(dt, 4), "mfu": round(mfu, 4),
                  "loss": float(loss), "backend": backend},
    }
    if not on_tpu:
        # the chip tunnel comes and goes; if it is down right now, surface
        # the most recent REAL TPU measurement (clearly labeled with its
        # timestamp) alongside the smoke number instead of erasing it
        last = _last_tpu_history()
        if last is not None:
            result["extra"]["last_tpu_measured"] = last
    print(json.dumps(result))
    # perf-regression history: tests/test_perf_guard.py compares the last
    # two same-backend/same-config entries
    try:
        # history entry: shallow-copy extra WITHOUT the nested
        # last_tpu_measured report field (it would re-embed the previous
        # TPU entry into every CPU line)
        extra = {k: v for k, v in result["extra"].items()
                 if k != "last_tpu_measured"}
        hist = dict(result, extra=extra, ts=time.time(), batch=batch,
                    seq=seq, remat=str(remat))
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BENCH_HISTORY.jsonl"), "a") as f:
            f.write(json.dumps(hist) + "\n")
    except OSError:
        pass


if __name__ == "__main__":
    main()
