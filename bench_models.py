"""Secondary benchmarks — BASELINE.json's non-Llama headline configs on
one chip: ResNet-50 (vision conv path), BERT-base (encoder path), MoE
decoder (expert path). The Llama pretrain headline lives in bench.py.

    python bench_models.py [resnet50] [bert] [moe]   # default: all

Prints one JSON line per model and appends each to BENCH_HISTORY.jsonl
(tagged with "model") so the perf guard can compare rounds. On CPU (no
chip / PT_BENCH_CPU=1) runs tiny smoke shapes.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from bench import PEAK_FLOPS, _data_rng, _tpu_alive


def _mesh1():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:1]), ("dp",))


def _time_steps(tr, batch, iters):
    import jax
    loss = tr.step(batch)  # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = tr.step(batch)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / iters, float(np.asarray(loss))


def bench_resnet50(on_tpu):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.parallel.trainer import Trainer

    bs, size, iters = (64, 224, 10) if on_tpu else (4, 32, 2)
    model = pt.vision.models.resnet50(num_classes=1000)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    ce = pt.nn.CrossEntropyLoss()

    def loss_fn(m, b):
        x, y = b
        logits = m(x)
        return ce(logits.astype("float32"), y)

    tr = Trainer(model, opt, loss_fn, mesh=_mesh1())
    rng = _data_rng()
    x = rng.randn(bs, 3, size, size).astype(
        np.float32 if not on_tpu else jnp.bfloat16)
    y = rng.randint(0, 1000, (bs,))
    dt, loss = _time_steps(tr, (x, y), iters)
    return {"imgs_per_sec": round(bs / dt, 1), "batch": bs,
            "step_time_s": round(dt, 4), "loss": loss}


def bench_bert(on_tpu):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models.bert import BertConfig, BertForSequenceClassification
    from paddle_tpu.parallel.trainer import Trainer

    if on_tpu:
        cfg = BertConfig()  # base: 12L/768H
        bs, seq, iters = 32, 128, 10
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=128)
        bs, seq, iters = 2, 16, 2
    model = BertForSequenceClassification(cfg, num_classes=2)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=5e-5,
                             parameters=model.parameters())
    ce = pt.nn.CrossEntropyLoss()

    def loss_fn(m, b):
        ids, y = b
        logits = m(ids)
        return ce(logits.astype("float32"), y)

    tr = Trainer(model, opt, loss_fn, mesh=_mesh1())
    rng = _data_rng()
    ids = rng.randint(0, cfg.vocab_size, (bs, seq))
    y = rng.randint(0, 2, (bs,))
    dt, loss = _time_steps(tr, (ids, y), iters)
    return {"seqs_per_sec": round(bs / dt, 1), "batch": bs, "seq": seq,
            "step_time_s": round(dt, 4), "loss": loss}


def bench_moe(on_tpu):
    """MoE decoder pretrain step (shared+routed experts, top-2 gating) —
    the DeepSeekMoE/Qwen2-MoE-style config from BASELINE.json."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models.moe_llm import MoEConfig, MoEForCausalLM
    from paddle_tpu.parallel.trainer import Trainer

    if on_tpu:
        cfg = MoEConfig(vocab_size=32000, hidden_size=1024,
                        intermediate_size=1408, num_hidden_layers=8,
                        num_attention_heads=16, num_key_value_heads=16,
                        num_experts=8, num_experts_per_tok=2,
                        max_position_embeddings=2048)
        bs, seq, iters = 8, 1024, 10
    else:
        cfg = MoEConfig.tiny_moe() if hasattr(MoEConfig, "tiny_moe") else \
            MoEConfig(vocab_size=256, hidden_size=64, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, num_experts=4,
                      num_experts_per_tok=2, max_position_embeddings=128)
        bs, seq, iters = 2, 32, 2
    model = MoEForCausalLM(cfg)
    for p in model.parameters():  # single-chip bench: no tp axis in mesh
        p.dist_spec = None
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=3e-4,
                             parameters=model.parameters())

    def loss_fn(m, b):
        ids, labels = b
        out = m(ids)
        logits = out[0] if isinstance(out, tuple) else out
        logp = pt.nn.functional.log_softmax(logits.astype("float32"), axis=-1)
        import paddle_tpu as _pt
        picked = _pt.take_along_axis(logp, labels.unsqueeze(-1), axis=-1)
        return -picked.mean()

    tr = Trainer(model, opt, loss_fn, mesh=_mesh1())
    rng = _data_rng()
    ids = rng.randint(0, cfg.vocab_size, (bs, seq))
    dt, loss = _time_steps(tr, (ids, ids), iters)
    return {"tokens_per_sec": round(bs * seq / dt, 1), "batch": bs,
            "seq": seq, "step_time_s": round(dt, 4), "loss": loss}


def bench_serving(on_tpu):
    """Continuous-batching decode throughput over the paged KV cache
    (pallas paged-attention kernel on chip) — the inference-side headline
    (reference: PaddleNLP predictor block_multihead_attention path)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_spmd as M
    from paddle_tpu.models.llama_serving import Request, ServingEngine

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        max_seqs, new_tok, nreq, dtype = 8, 128, 16, jnp.bfloat16
        max_seq_len, page = 1024, 16
    else:
        cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                               kv_heads=2, ffn=128)
        max_seqs, new_tok, nreq, dtype = 2, 8, 3, jnp.float32
        max_seq_len, page = 64, 8
    params = M.init_params(cfg, seed=0, dtype=dtype)
    # PT_SERVE_CACHE=int8: quantized KV pool (halves HBM per token;
    # autotune/capture sweep both on chip). Fail fast on anything else
    # — a typo must not burn a capture window deep in engine init.
    cache_dtype = os.environ.get("PT_SERVE_CACHE") or None
    if cache_dtype not in (None, "int8"):
        raise SystemExit(
            f"PT_SERVE_CACHE={cache_dtype!r} unsupported; use 'int8' or "
            "unset (pool stores the model dtype)")
    # PT_SERVE_SPEC=G: prompt-lookup speculative decoding, G-token
    # verify chunks (greedy-exact; see llama_serving.verify_step)
    spec = int(os.environ.get("PT_SERVE_SPEC", "0") or 0)
    # PT_SERVE_PREFIX=1: shared-prefix workload over the prefix KV
    # cache (serving/kvcache.py) — every prompt reuses one long common
    # header (the system-prompt / few-shot pattern), so admissions
    # after the first map the header's pages and prefill only the tail
    prefix_mode = (os.environ.get("PT_SERVE_PREFIX", "") or "0") \
        not in ("", "0")
    # PT_SERVE_ROUTER=1: scale-out tier — a prefix-affinity router over
    # 2 engine replicas vs ONE engine at equal total capacity, on a
    # shared-system-prompt workload (serving/router.py)
    if (os.environ.get("PT_SERVE_ROUTER", "") or "0") not in ("", "0"):
        return _bench_serving_router(on_tpu, params, cfg, dtype)
    # PT_SERVE_DISAGG=1: disaggregated prefill/decode — 1 prefill + 1
    # decode replica with KV handoff vs 2 "both" replicas at equal
    # capacity, on a mixed long-prompt + chatty-decode workload
    # (docs/serving.md § Disaggregated prefill/decode)
    if (os.environ.get("PT_SERVE_DISAGG", "") or "0") not in ("", "0"):
        return _bench_serving_disagg(on_tpu, params, cfg, dtype)
    # PT_SERVE_FLEET=1: multi-host fleet plane — 1 prefill + 1 decode
    # worker spawned as SUBPROCESSES on loopback behind the unchanged
    # router, vs the in-process router on the same seeded workload;
    # token identity asserted and handoff bytes/sec measured over the
    # real socket (serving/fleet.py; docs/serving.md § Fleet plane)
    if (os.environ.get("PT_SERVE_FLEET", "") or "0") not in ("", "0"):
        return _bench_serving_fleet(on_tpu, params, cfg, dtype)
    # PT_SERVE_MULTITURN=1: multi-turn conversations returning after a
    # cache-thrashing burst — the host-RAM KV tier (serving/kvtier.py)
    # vs a tier-off baseline at token-identical outputs
    if (os.environ.get("PT_SERVE_MULTITURN", "") or "0") not in ("", "0"):
        return _bench_serving_multiturn(on_tpu, params, cfg, dtype)
    # PT_SERVE_PIPELINE=1: the double-buffered pump + device-side
    # sampling vs the synchronous pump at equal config and
    # token-identical outputs (serving/scheduler.py; ROADMAP item 4)
    if (os.environ.get("PT_SERVE_PIPELINE", "") or "0") not in ("", "0"):
        return _bench_serving_pipeline(on_tpu, params, cfg, dtype)
    # PT_SERVE_CHAOS=1: crash-recovery drill — a seeded fault plan
    # injects a device failure mid-run; survivors must be
    # token-identical to an undisturbed baseline and the artifact
    # reports goodput retained (serving/faults.py; docs/reliability.md)
    if (os.environ.get("PT_SERVE_CHAOS", "") or "0") not in ("", "0"):
        return _bench_serving_chaos(on_tpu, params, cfg, dtype)
    # PT_SERVE_RAGGED=1: the unified ragged step vs the bucketed entry
    # points at equal config and token-identical outputs — tracked
    # compiles, pad tokens, measured MFU and tok/s for both sides
    # (docs/serving.md § Unified ragged step)
    if (os.environ.get("PT_SERVE_RAGGED", "") or "0") not in ("", "0"):
        return _bench_serving_ragged(on_tpu, params, cfg, dtype)
    # PT_SERVE_LEAN=1 (bench mode): the row-sparse lm_head epilogue vs
    # the full-logits step at equal config and token-identical outputs
    # — unembed FLOPs saved, logit rows skipped, tok/s for both sides
    # (docs/serving.md § Lean epilogue)
    if (os.environ.get("PT_SERVE_LEAN", "") or "0") not in ("", "0"):
        return _bench_serving_lean(on_tpu, params, cfg, dtype)
    # PT_SERVE_SLO=1: the SLO/goodput accounting plane — a mixed
    # interactive + batch workload measured through the per-request
    # timeline ledger: goodput ratio, attained/violated by class,
    # violations attributed to phases, per-phase latency percentiles
    # (docs/observability.md § Request timelines & SLO accounting)
    if (os.environ.get("PT_SERVE_SLO", "") or "0") not in ("", "0"):
        return _bench_serving_slo(on_tpu, params, cfg, dtype)
    # PT_SERVE_PULSE=1 (bench mode): the telemetry pulse plane smoke —
    # the sampler's per-tick self-cost stays bounded against a live
    # registry, and a forced-stall drill (seeded FaultPlan delay) lands
    # as a step-time spike in the rings plus EXACTLY ONE rate-limited
    # capture bundle (docs/observability.md § Pulse & capture bundles)
    if (os.environ.get("PT_SERVE_PULSE", "") or "0") not in ("", "0"):
        return _bench_serving_pulse(on_tpu, params, cfg, dtype)

    rng = _data_rng()
    if prefix_mode:
        if not on_tpu:
            nreq = max(nreq, 4)
        header = list(map(int, rng.randint(1, cfg.vocab_size, 3 * page)))
        prompts = [header + list(map(int, rng.randint(
            1, cfg.vocab_size, 4 if not on_tpu else 16)))
            for _ in range(nreq)]
    elif spec > 1:
        # speculative decoding exists for workloads with n-gram
        # repetition (code, templated text, retrieval contexts);
        # uniform-random prompts draft at ~0% acceptance and would show
        # the feature doing nothing. Build each prompt as a SHORT motif
        # repeated enough times that prompt_lookup_draft's ngram match
        # always lands (>=3 full repeats — r4's bench used a 6-token
        # motif inside a 3-token CPU prompt, which can never repeat, so
        # the published artifact showed accept_rate 0.0; VERDICT r4
        # weak #1). Generations must also be LONG: greedy decode from a
        # repetitive prompt settles into short loops after ~10 tokens
        # and that loop regime (accept→1) is where drafting pays; short
        # generations spend their whole budget in the non-loopy warm-in.
        # On CPU the verify forward costs real FLOPs (~1.9x a decode
        # step at G=4, measured), so the wall-clock win only appears
        # once the step ratio clears that — new_tok=256 does (measured
        # +7% tok/s, 1.9x fewer device steps); on TPU decode is
        # HBM-bound so verify is near-free and shorter runs win too.
        if not on_tpu:
            max_seqs, new_tok, max_seq_len = 4, 256, 512
        else:
            # 256 new tokens, not 128: the first TPU spec entry
            # (2026-08-01, accept 0.419, spec_speedup 0.83) showed 128
            # spends too much of the budget in the pre-loop warm-in
            # where prompt-lookup drafts diverge from the model; the
            # loop regime that pays for drafting needs the longer run,
            # exactly as the CPU branch above found at 256. Capped so
            # prompt (<64 tokens) + generation always fits the pool.
            new_tok = min(max(new_tok, 256), max_seq_len - 64)
        prompts = []
        for _ in range(nreq):
            motif = list(map(int, rng.randint(1, cfg.vocab_size, 3)))
            reps = int(rng.randint(4, 8)) if on_tpu else 4
            prompts.append((motif * reps)[:-1])
    else:
        prompts = [list(map(int, rng.randint(
            1, cfg.vocab_size, int(rng.randint(8, 64)) if on_tpu else 3)))
            for _ in range(nreq)]

    def run_once(spec_g, warm=True):
        # warmup pass first: the jitted prefill/decode/verify fns
        # compile once per process, and whichever engine runs first
        # would otherwise eat every compile in its wall-clock — the
        # spec-vs-plain comparison must time both sides warm. A few
        # tokens warm the identical compile cache (same prompts → same
        # prefill buckets; decode/verify widths are shape-fixed), so
        # don't replay the full workload — on TPU the discarded run
        # would burn capture-window minutes.
        nt = new_tok if warm else min(new_tok, 2 * max(spec_g, 2))
        if warm:
            run_once(spec_g, warm=False)
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, cache_dtype=cache_dtype,
                            spec_decode=spec_g,
                            prefix_cache=prefix_mode)
        # serving-runtime telemetry rides the same engine hooks the
        # HTTP frontend uses; the timed run's snapshot ships in the
        # artifact so the driver sees TTFT/occupancy, not just tok/s
        from paddle_tpu.serving.metrics import (EngineMetrics,
                                                MetricsRegistry)
        eng._bench_registry = MetricsRegistry()
        eng.metrics = EngineMetrics(eng._bench_registry)
        for i, prompt in enumerate(prompts):
            eng.submit(Request(f"r{i}", prompt, max_new_tokens=nt))
        # device telemetry window: XLA-counted FLOPs issued by the
        # prefill/decode/verify entry points during THIS timed run →
        # measured MFU instead of an analytic-formula estimate
        from paddle_tpu.observability import device_telemetry as _dt
        mark = _dt.COSTS.issued_totals()
        t0 = time.perf_counter()
        done = eng.run()
        dt = time.perf_counter() - t0
        issued = _dt.COSTS.issued_totals()
        d_flops = issued["flops"] - mark["flops"]
        eng._bench_device = {
            "mfu": _dt.COSTS.mfu_over(d_flops, dt),
            "flops": d_flops,
            "phase_flops": {
                name.replace("serving.", ""):
                    v["flops"] - mark["per_fn"].get(
                        name, {"flops": 0.0})["flops"]
                for name, v in issued["per_fn"].items()
                if name.startswith("serving.")
                and v["flops"] - mark["per_fn"].get(
                    name, {"flops": 0.0})["flops"] > 0},
        }
        return eng, done, dt

    eng, done, dt = run_once(spec)
    total_new = sum(len(r.output) for r in done)
    # the int8 cache's capacity win, measured not claimed (VERDICT r4
    # weak #4): bytes of KV pool (incl. scales) per servable token —
    # int8 fits ~2x (bf16) / ~3.5x (fp32) the tokens per HBM byte
    pool_bytes = int(eng.k_pool.nbytes + eng.v_pool.nbytes
                     + (eng.k_scale.nbytes + eng.v_scale.nbytes
                        if eng.cache_quant else 0))
    capacity_tokens = (eng.num_pages - 1) * eng.page_size
    snap = eng._bench_registry.snapshot()
    # HBM high-water (device allocator stats on chip; live-array walk
    # everywhere) — the capacity number int8-cache claims are judged by
    from paddle_tpu.observability import device_telemetry as _devtel
    mem = _devtel.ACCOUNTANT.poll(force=True)
    hbm_peak = mem.get("peak_bytes_in_use") or mem["live_peak_bytes"]
    out = {"decode_tokens_per_sec": round(total_new / dt, 1),
           "requests": nreq, "new_tokens": total_new, "batch": max_seqs,
           "cache_dtype": cache_dtype or str(jnp.dtype(dtype).name),
           "kv_pool_bytes": pool_bytes,
           "kv_bytes_per_token": round(pool_bytes / capacity_tokens, 1),
           "step_time_s": round(dt / max(total_new, 1), 5),
           "mfu": round(eng._bench_device["mfu"], 6),
           "xla_flops": eng._bench_device["flops"],
           "phase_flops": eng._bench_device["phase_flops"],
           "hbm_peak_bytes": int(hbm_peak),
           "metrics": {
               "ttft_p50_s": round(snap["pt_serving_ttft_seconds"]["p50"], 5),
               "ttft_p99_s": round(snap["pt_serving_ttft_seconds"]["p99"], 5),
               "ttft_count": snap["pt_serving_ttft_seconds"]["count"],
               "tpot_p50_s": round(snap["pt_serving_tpot_seconds"]["p50"], 6),
               "queue_depth_peak":
                   snap["pt_serving_queue_depth_peak"]["value"],
               "batch_occupancy":
                   snap["pt_serving_batch_occupancy"]["value"],
               "generated_tokens":
                   snap["pt_serving_generated_tokens"]["value"],
               "device_steps": snap["pt_serving_device_steps"]["value"],
               "preemptions": snap["pt_serving_preemptions"]["value"],
               "page_allocs": snap["pt_serving_page_allocs"]["value"],
               # host time between device-step launches (ISSUE 8):
               # the sync-loop number the pipelined pump shrinks
               "host_gap_p50_s":
                   round(snap["pt_step_host_gap_seconds"]["p50"], 6),
               "host_gap_count":
                   snap["pt_step_host_gap_seconds"]["count"],
           },
           "loss": 0.0}
    if prefix_mode:
        # the prefix cache's own ledger — the artifact must show the
        # reuse the workload was built to exercise
        pc = eng.prefix_cache
        out["workload"] = "shared-prefix"
        out["prefix_hit_rate"] = round(pc.hit_rate, 3)
        out["tokens_reused"] = int(pc.tokens_reused)
        out["prefix_evictions"] = int(pc.evictions)
    if spec > 1:
        # plain decode on the IDENTICAL workload, same engine config —
        # the artifact must carry its own comparison point
        peng, pdone, pdt = run_once(0)
        ptotal = sum(len(r.output) for r in pdone)
        out["spec_decode"] = spec
        out["workload"] = "ngram-repetitive"
        out["device_steps"] = eng.device_steps
        out["spec_accept_rate"] = round(
            eng.spec_accepted / max(eng.spec_drafted, 1), 3)
        out["plain_device_steps"] = peng.device_steps
        out["plain_decode_tokens_per_sec"] = round(ptotal / pdt, 1)
        out["spec_speedup"] = round((total_new / dt) / (ptotal / pdt), 3)
    return out


def _bench_serving_ragged(on_tpu, params, cfg, dtype):
    """PT_SERVE_RAGGED=1: the unified ragged step vs the bucketed entry
    points at equal config and TOKEN-IDENTICAL outputs. Shared-prefix
    workload (the mix buckets handle worst): the first admission
    prefills the whole prompt, later ones suffix-prefill behind a
    prefix-cache hit, and decodes interleave throughout — the bucketed
    side compiles one program per (entry point x bucket) that mix
    visits, the ragged side compiles `unified_step` once and pays zero
    pad tokens. The artifact carries tracked compiles (cold pass),
    pad/ragged token counters, measured MFU and tok/s for both sides."""
    from paddle_tpu.models.llama_serving import Request, ServingEngine
    from paddle_tpu.observability import compile_telemetry as _ct
    from paddle_tpu.observability import device_telemetry as _dt
    from paddle_tpu.serving.metrics import EngineMetrics, MetricsRegistry

    if on_tpu:
        max_seqs, new_tok, nreq = 8, 64, 12
        max_seq_len, page = 1024, 16
    else:
        max_seqs, new_tok, nreq = 2, 8, 4
        max_seq_len, page = 64, 8
    rng = _data_rng()
    header = list(map(int, rng.randint(1, cfg.vocab_size, 3 * page)))
    prompts = [header + list(map(int, rng.randint(
        1, cfg.vocab_size, 16 if on_tpu else 4))) for _ in range(nreq)]

    def run_once(ragged, nt):
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, prefix_cache=True, ragged=ragged,
                            use_pallas=None if on_tpu else False)
        reg = MetricsRegistry()
        eng.metrics = EngineMetrics(reg)
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=nt))
        mark = _dt.COSTS.issued_totals()
        t0 = time.perf_counter()
        done = eng.run()
        dt = time.perf_counter() - t0
        d_flops = _dt.COSTS.issued_totals()["flops"] - mark["flops"]
        return {"outs": {r.rid: r.output for r in done},
                "new_tokens": sum(len(r.output) for r in done),
                "tok_s": sum(len(r.output) for r in done) / dt,
                "mfu": _dt.COSTS.mfu_over(d_flops, dt),
                "pad_tokens": int(eng.pad_tokens),
                "ragged_tokens": int(eng.ragged_tokens),
                "device_steps": int(eng.device_steps),
                "pad_total": reg.snapshot()["pt_pad_tokens"]["value"]}

    def run_mode(ragged):
        # cold pass (short generations, same admission mix) pays and
        # COUNTS the mode's compiles; the timed pass runs warm
        c0 = _ct.REGISTRY.totals()["compiles"]
        run_once(ragged, min(new_tok, 2))
        compiles = _ct.REGISTRY.totals()["compiles"] - c0
        res = run_once(ragged, new_tok)
        res["compiles"] = compiles
        return res

    bucketed = run_mode(False)
    ragged = run_mode(True)
    return {
        "workload": "ragged-vs-bucketed (shared-prefix)",
        "outputs_match": bucketed["outs"] == ragged["outs"],
        "requests": nreq, "new_tokens": ragged["new_tokens"],
        "batch": max_seqs,
        "decode_tokens_per_sec": round(ragged["tok_s"], 1),
        "step_time_s": round(1.0 / max(ragged["tok_s"], 1e-9), 5),
        "bucketed_decode_tokens_per_sec": round(bucketed["tok_s"], 1),
        "tok_s_delta": round(
            ragged["tok_s"] / max(bucketed["tok_s"], 1e-9) - 1.0, 4),
        "compiles": ragged["compiles"],
        "bucketed_compiles": bucketed["compiles"],
        "pad_tokens": ragged["pad_tokens"],
        "bucketed_pad_tokens": bucketed["pad_tokens"],
        "pt_pad_tokens_total": ragged["pad_total"],
        "ragged_tokens": ragged["ragged_tokens"],
        "device_steps": ragged["device_steps"],
        "bucketed_device_steps": bucketed["device_steps"],
        "pt_mfu": round(ragged["mfu"], 6),
        "bucketed_pt_mfu": round(bucketed["mfu"], 6),
        "loss": 0.0,
    }


def _bench_serving_lean(on_tpu, params, cfg, dtype):
    """PT_SERVE_LEAN=1: the row-sparse lm_head epilogue (ISSUE 12) vs
    the full-logits unified step at equal config and TOKEN-IDENTICAL
    outputs. Prefill-heavy shared-prefix workload — the regime the
    epilogue targets: chunked prefill runs push T far past the handful
    of rows that actually sample, so the full step burns a
    (T, vocab) unembed mostly on rows nobody reads. The artifact
    carries `outputs_match`, the unembed FLOPs both sides issued
    through `serving.unified_step` (CostRegistry per-fn XLA analysis,
    not an analytic formula), the pt_logit_rows(_skipped) ledgers, and
    tok/s for both sides."""
    from paddle_tpu.models.llama_serving import Request, ServingEngine
    from paddle_tpu.observability import compile_telemetry as _ct
    from paddle_tpu.observability import device_telemetry as _dt
    from paddle_tpu.serving.metrics import EngineMetrics, MetricsRegistry

    if on_tpu:
        max_seqs, new_tok, nreq = 8, 64, 12
        max_seq_len, page = 1024, 16
    else:
        max_seqs, new_tok, nreq = 2, 8, 4
        max_seq_len, page = 64, 8
    rng = _data_rng()
    header = list(map(int, rng.randint(1, cfg.vocab_size, 3 * page)))
    prompts = [header + list(map(int, rng.randint(
        1, cfg.vocab_size, 16 if on_tpu else 4))) for _ in range(nreq)]

    def run_once(lean, nt):
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, prefix_cache=True, ragged=True,
                            lean=lean,
                            use_pallas=None if on_tpu else False)
        reg = MetricsRegistry()
        eng.metrics = EngineMetrics(reg)
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=nt))
        mark = _dt.COSTS.issued_totals()
        t0 = time.perf_counter()
        done = eng.run()
        dt = time.perf_counter() - t0
        issued = _dt.COSTS.issued_totals()

        def fn_flops(name):
            return issued["per_fn"].get(name, {"flops": 0.0})["flops"] \
                - mark["per_fn"].get(name, {"flops": 0.0})["flops"]
        snap = reg.snapshot()
        return {"outs": {r.rid: r.output for r in done},
                "new_tokens": sum(len(r.output) for r in done),
                "tok_s": sum(len(r.output) for r in done) / dt,
                "step_flops": fn_flops("serving.unified_step"),
                "logit_rows": int(eng.logit_rows),
                "logit_rows_skipped": int(eng.logit_rows_skipped),
                "pt_logit_rows": snap["pt_logit_rows"]["value"],
                "pt_logit_rows_skipped":
                    snap["pt_logit_rows_skipped"]["value"]}

    def run_mode(lean):
        # cold pass (short generations, same admission mix) pays and
        # COUNTS the mode's compiles; the timed pass runs warm
        c0 = _ct.REGISTRY.totals()["compiles"]
        run_once(lean, min(new_tok, 2))
        compiles = _ct.REGISTRY.totals()["compiles"] - c0
        res = run_once(lean, new_tok)
        res["compiles"] = compiles
        return res

    full = run_mode(False)
    lean = run_mode(True)
    # the epilogue's whole claim, asserted in the artifact path itself:
    # identical tokens from a strictly cheaper step program
    assert lean["step_flops"] < full["step_flops"], (
        lean["step_flops"], full["step_flops"])
    assert lean["logit_rows_skipped"] > 0
    return {
        "workload": "lean-vs-full epilogue (shared-prefix)",
        "outputs_match": full["outs"] == lean["outs"],
        "requests": nreq, "new_tokens": lean["new_tokens"],
        "batch": max_seqs,
        "decode_tokens_per_sec": round(lean["tok_s"], 1),
        "step_time_s": round(1.0 / max(lean["tok_s"], 1e-9), 5),
        "full_decode_tokens_per_sec": round(full["tok_s"], 1),
        "tok_s_delta": round(
            lean["tok_s"] / max(full["tok_s"], 1e-9) - 1.0, 4),
        "unified_step_flops": lean["step_flops"],
        "full_unified_step_flops": full["step_flops"],
        "unembed_flops_saved": round(
            1.0 - lean["step_flops"] / max(full["step_flops"], 1e-9), 4),
        "logit_rows": lean["logit_rows"],
        "logit_rows_skipped": lean["logit_rows_skipped"],
        "pt_logit_rows_total": lean["pt_logit_rows"],
        "pt_logit_rows_skipped_total": lean["pt_logit_rows_skipped"],
        "compiles": lean["compiles"],
        "full_compiles": full["compiles"],
        "loss": 0.0,
    }


def _bench_serving_pipeline(on_tpu, params, cfg, dtype):
    """PT_SERVE_PIPELINE=1: kill the per-step host round-trip. The same
    workload — a mix of greedy and seeded-sampling requests — runs
    through the RequestScheduler twice at equal engine config: once
    with the synchronous pump (launch -> blocked read -> bookkeeping ->
    launch) and once with the double-buffered pump (launch N+1 before
    consuming N; sampling/stop conditions already evaluated on device).
    The artifact carries `outputs_match` (token-identical is the
    contract, greedy AND seeded sampling), the measured
    pt_step_host_gap_seconds distribution for both pumps, and the
    tok/s delta."""
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving.metrics import MetricsRegistry
    from paddle_tpu.serving.scheduler import RequestScheduler

    if on_tpu:
        max_seqs, new_tok, nreq = 8, 128, 16
        max_seq_len, page = 1024, 16
    else:
        max_seqs, new_tok, nreq = 4, 32, 8
        max_seq_len, page = 128, 8
    rng = _data_rng()
    reqs = []
    for i in range(nreq):
        prompt = list(map(int, rng.randint(
            1, cfg.vocab_size, int(rng.randint(8, 48)) if on_tpu else 4)))
        kw = {"max_new_tokens": new_tok}
        if i % 3 == 2:   # every third request samples, seeded
            kw.update(temperature=0.8, top_k=8, top_p=0.95, seed=100 + i)
        reqs.append((prompt, kw))

    def run_pump(pipeline, warm=True):
        if warm:
            # full-trajectory warmup (same pattern as the multiturn
            # bench): admission-wave composition decides which varlen
            # prefill buckets compile, so a scaled-down warm run leaves
            # a first-wave compile inside the timed region — and the
            # sync-vs-pipelined comparison must time both sides warm
            run_pump(pipeline, warm=False)
        # lean=False: this bench isolates the PUMP variable — the
        # double-buffered pump hides the blocked device read inside the
        # step gap, and the lean epilogue shrinks that same read, so
        # with lean on there is little left to hide at smoke scale and
        # the sync-vs-pipelined gap ordering becomes noise. The lean
        # epilogue has its own A/B mode (PT_SERVE_LEAN=1).
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, lean=False,
                            use_pallas=None if on_tpu else False)
        sched = RequestScheduler(eng, max_queue=nreq,
                                 metrics=MetricsRegistry(),
                                 pipeline=pipeline)
        # submit under pause(): the pump sees the whole queue at once,
        # so the admission-wave composition — and with it the varlen
        # prefill bucket set — is identical for every run instead of a
        # race against the submitting thread (a wave-size change is a
        # fresh prefill bucket, i.e. an XLA compile inside the timing)
        sched.pause()
        t0 = time.perf_counter()
        handles = [sched.submit(prompt, **kw) for prompt, kw in reqs]
        sched.resume()
        outs = [h.result(timeout=600) for h in handles]
        dt = time.perf_counter() - t0
        snap = sched.metrics_snapshot()
        sched.shutdown(drain=True, timeout=60)
        total = sum(len(o) for o in outs)
        return outs, total / dt, snap

    sync_outs, sync_tps, sync_snap = run_pump(False)
    pipe_outs, pipe_tps, pipe_snap = run_pump(True)

    def gap(snap):
        h = snap["pt_step_host_gap_seconds"]
        return {"p50_s": round(h["p50"], 6), "p99_s": round(h["p99"], 6),
                "mean_s": round(h["sum"] / max(h["count"], 1), 6),
                "count": h["count"]}
    sync_gap, pipe_gap = gap(sync_snap), gap(pipe_snap)
    return {
        "workload": "pipelined-pump",
        "outputs_match": sync_outs == pipe_outs,
        "requests": nreq, "new_tokens": sum(len(o) for o in pipe_outs),
        "batch": max_seqs,
        "decode_tokens_per_sec": round(pipe_tps, 1),
        "sync_decode_tokens_per_sec": round(sync_tps, 1),
        "tok_s_delta": round(pipe_tps / max(sync_tps, 1e-9) - 1.0, 4),
        "host_gap_sync": sync_gap,
        "host_gap_pipelined": pipe_gap,
        "host_gap_reduction": round(
            1.0 - pipe_gap["mean_s"] / max(sync_gap["mean_s"], 1e-12), 4),
        "pipeline_depth": pipe_snap["pt_pipeline_depth"]["value"],
        "loss": 0.0,
    }


def _bench_serving_chaos(on_tpu, params, cfg, dtype):
    """PT_SERVE_CHAOS=1: the crash-recovery drill (ISSUE 9). The same
    mixed greedy + seeded-sampling workload runs three times at equal
    engine config: once undisturbed (the baseline), then under a
    seeded `FaultPlan` that kills a device step mid-run — once with
    the synchronous pump and once with the pipelined pump (a pending
    step_finish ticket in flight at crash time). Warm restart must
    requeue every victim and finish them token-identical to the
    baseline; the artifact asserts `outputs_match`, carries the
    restart/requeue ledger, and reports goodput retained (completed
    tokens / baseline tokens — 1.0 when recovery loses nothing)."""
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving import FaultPlan, MetricsRegistry, \
        RequestScheduler

    if on_tpu:
        max_seqs, new_tok, nreq = 8, 64, 12
        max_seq_len, page = 512, 16
        fault_spec = "step_launch:raise@12"
    else:
        max_seqs, new_tok, nreq = 4, 16, 6
        max_seq_len, page = 128, 8
        fault_spec = "step_launch:raise@4"
    rng = _data_rng()
    reqs = []
    for i in range(nreq):
        prompt = list(map(int, rng.randint(
            1, cfg.vocab_size, int(rng.randint(8, 32)) if on_tpu else 4)))
        kw = {"max_new_tokens": new_tok}
        if i % 3 == 2:   # every third request samples, seeded
            kw.update(temperature=0.8, top_k=8, top_p=0.95, seed=200 + i)
        reqs.append((prompt, kw))

    def run_drill(spec, pipeline, warm=True):
        if warm:
            # full-trajectory warmup: the chaos-vs-baseline comparison
            # must time both sides with identical compile caches (same
            # reasoning as the pipeline bench)
            run_drill(spec, pipeline, warm=False)
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, prefix_cache=True,
                            use_pallas=None if on_tpu else False,
                            faults=FaultPlan(spec) if spec else None)
        sched = RequestScheduler(eng, max_queue=nreq,
                                 metrics=MetricsRegistry(),
                                 pipeline=pipeline)
        # submit under pause(): deterministic admission waves (and so a
        # deterministic Nth-device-step crash position) per run
        sched.pause()
        t0 = time.perf_counter()
        handles = [sched.submit(prompt, **kw) for prompt, kw in reqs]
        sched.resume()
        outs, failed = [], 0
        for h in handles:
            try:
                outs.append(h.result(timeout=600))
            except Exception:  # noqa: BLE001 — drill counts casualties
                outs.append(None)
                failed += 1
        dt = time.perf_counter() - t0
        st = sched.stats()
        snap = sched.metrics_snapshot()
        sched.shutdown(drain=True, timeout=60)
        return outs, failed, dt, st, snap

    base_outs, base_failed, base_dt, _, _ = run_drill(None, False)
    assert base_failed == 0, "baseline run must not fail"
    base_tokens = sum(len(o) for o in base_outs)

    out = {"workload": "chaos-recovery", "requests": nreq,
           "batch": max_seqs, "fault_plan": fault_spec,
           "baseline_tokens_per_sec": round(base_tokens / base_dt, 1),
           "loss": 0.0}
    for name, pipeline in (("sync", False), ("pipelined", True)):
        outs, failed, dt, st, snap = run_drill(fault_spec, pipeline)
        done_tokens = sum(len(o) for o in outs if o is not None)
        led = st["requests"]
        out[name] = {
            "outputs_match": outs == base_outs,
            "failed_requests": failed,
            "restarts": int(snap["pt_engine_restarts"]["value"]),
            "requeued": int(snap["pt_requests_requeued"]["value"]),
            "quarantined": int(snap["pt_poison_quarantined"]["value"]),
            "restart_p50_s": round(
                snap["pt_engine_restart_seconds"]["p50"], 6),
            "goodput_retained": round(done_tokens / max(base_tokens, 1),
                                      4),
            "tokens_per_sec": round(done_tokens / dt, 1),
            "ledger_balanced": led["submitted"] == (
                led["completed"] + led["failed"] + led["cancelled"]
                + led["expired"] + st["queued"] + st["inflight"]),
        }
        # a transient fault must cost NOTHING: every survivor
        # token-identical, zero failures, ledger conserved
        assert out[name]["outputs_match"], (name, out[name])
        assert out[name]["restarts"] >= 1 and out[name]["requeued"] >= 1
        assert out[name]["ledger_balanced"], (name, out[name])
    out["outputs_match"] = (out["sync"]["outputs_match"]
                            and out["pipelined"]["outputs_match"])
    out["decode_tokens_per_sec"] = out["pipelined"]["tokens_per_sec"]
    return out


def _bench_serving_router(on_tpu, params, cfg, dtype):
    """PT_SERVE_ROUTER=1: the scale-out serving tier. Two independent
    engine replicas (own KV pool + prefix cache + scheduler pump each)
    behind the prefix-affinity Router serve a shared-system-prompt
    workload (G prompt groups, each group one hot header + distinct
    tails); the comparison point is ONE engine at equal total capacity
    (2x the slots and pages) on the identical prompts. The artifact
    carries the router ledger (dispatches / affinity hit rate / spills
    / failovers), aggregate tokens/sec for both topologies, and the
    per-replica balance + prefix-hit-rate the affinity routing is
    supposed to produce."""
    from paddle_tpu.models.llama_serving import Request, ServingEngine
    from paddle_tpu.serving import Router, build_replicas

    if on_tpu:
        per_seqs, groups, per_group, new_tok = 4, 8, 6, 64
        max_seq_len, page, tail = 1024, 16, 16
    else:
        per_seqs, groups, per_group, new_tok = 2, 4, 3, 8
        max_seq_len, page, tail = 64, 8, 4
    rng = _data_rng()
    headers = [list(map(int, rng.randint(1, cfg.vocab_size, 2 * page)))
               for _ in range(groups)]
    prompts = [h + list(map(int, rng.randint(1, cfg.vocab_size, tail)))
               for h in headers for _ in range(per_group)]

    def factory(i):
        return ServingEngine(params, cfg, max_seqs=per_seqs,
                             max_seq_len=max_seq_len, page_size=page,
                             dtype=dtype, prefix_cache=True,
                             use_pallas=None if on_tpu else False)

    def run_router(warm=True):
        if warm:
            run_router(warm=False)   # compile cache warm, same shapes
        router = Router(build_replicas(factory, 2,
                                       max_queue=len(prompts)))
        nt = new_tok if warm else 2
        t0 = time.perf_counter()
        handles = [router.submit(p, max_new_tokens=nt) for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        dt = time.perf_counter() - t0
        if not warm:
            router.shutdown(drain=True, timeout=60)
        return router, outs, dt

    def run_single(warm=True):
        if warm:
            run_single(warm=False)
        eng = ServingEngine(params, cfg, max_seqs=2 * per_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, prefix_cache=True,
                            use_pallas=None if on_tpu else False)
        nt = new_tok if warm else 2
        for i, p in enumerate(prompts):
            eng.submit(Request(f"s{i}", p, max_new_tokens=nt))
        t0 = time.perf_counter()
        done = eng.run()
        return eng, done, time.perf_counter() - t0

    router, outs, rdt = run_router()
    seng, sdone, sdt = run_single()
    total = sum(len(o) for o in outs)
    stotal = sum(len(r.output) for r in sdone)
    rstats = router.stats()
    per_replica = {}
    n_disp = max(int(router.dispatches.value), 1)
    for rid in router.replica_ids:
        rep = router.replica(rid)
        snap = rep.registry.snapshot()
        rs = rstats["replicas"][rid]
        per_replica[rid] = {
            "dispatches": rs["dispatches"],
            "share": round(rs["dispatches"] / n_disp, 3),
            "prefix_hit_rate":
                round(snap["pt_prefix_hit_rate"]["value"], 3),
            "generated_tokens":
                int(snap["pt_serving_generated_tokens"]["value"]),
            "requests": rs["requests"],
        }
    shares = [v["share"] for v in per_replica.values()]
    out = {
        "workload": "router-shared-prefix",
        "replicas": 2, "requests": len(prompts),
        "groups": groups, "new_tokens": total,
        "router_dispatches": int(router.dispatches.value),
        "affinity_hit_rate": round(
            router.affinity_hits.value / n_disp, 3),
        "spills": int(router.spills.value),
        "failovers": int(router.failovers.value),
        # balance: smallest/largest replica share of dispatches (1.0 =
        # perfectly even; group->replica placement is consistent-hash,
        # so skew reflects the key distribution, not a bug)
        "replica_balance": round(min(shares) / max(shares), 3)
        if max(shares) > 0 else 0.0,
        "per_replica": per_replica,
        "aggregate_tokens_per_sec": round(total / rdt, 1),
        "single_engine_tokens_per_sec": round(stotal / sdt, 1),
        "router_speedup": round((total / rdt) / (stotal / sdt), 3),
        "single_engine_prefix_hit_rate":
            round(seng.prefix_cache.hit_rate, 3),
        "loss": 0.0,
    }
    router.shutdown(drain=True, timeout=60)
    return out


def _bench_serving_disagg(on_tpu, params, cfg, dtype):
    """PT_SERVE_DISAGG=1: disaggregated prefill/decode serving. One
    prefill-role + one decode-role replica (KV pages migrate through
    serving/handoff.py after each prompt is prefilled and seeded) vs
    two "both"-role replicas at EQUAL total capacity on the identical
    mixed workload: long-prompt requests (prefill-heavy, few output
    tokens) interleaved with chatty short-prompt requests (decode-
    heavy) — the interference pattern disaggregation exists to remove.
    Outputs must be token-identical across topologies; the artifact
    carries the handoff ledger (exports/imports/bytes, degradations),
    decode-TPOT percentiles for both sides, per-role analytic MFU, and
    the scheduler ledgers balanced INCLUDING the "handoff" terminal
    state."""
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving import Router, build_replicas

    if on_tpu:
        per_seqs, page, max_seq_len = 4, 16, 1024
        n_long, n_chat, long_len, chat_len = 6, 6, 384, 12
        long_new, chat_new = 12, 96
        tier_bytes = 256 << 20
    else:
        per_seqs, page, max_seq_len = 2, 8, 64
        n_long, n_chat, long_len, chat_len = 3, 3, 24, 4
        long_new, chat_new = 4, 10
        tier_bytes = 8 << 20
    rng = _data_rng()
    long_p = [list(map(int, rng.randint(1, cfg.vocab_size, long_len)))
              for _ in range(n_long)]
    chat_p = [list(map(int, rng.randint(1, cfg.vocab_size, chat_len)))
              for _ in range(n_chat)]
    # interleave so prefill pressure and decode pressure overlap in
    # time — back-to-back phases would hide the interference
    work = []
    for i in range(max(n_long, n_chat)):
        if i < n_long:
            work.append((long_p[i], long_new))
        if i < n_chat:
            work.append((chat_p[i], chat_new))

    def factory(i):
        return ServingEngine(params, cfg, max_seqs=per_seqs,
                             max_seq_len=max_seq_len, page_size=page,
                             dtype=dtype, prefix_cache=True,
                             host_tier_bytes=tier_bytes,
                             use_pallas=None if on_tpu else False)

    from paddle_tpu.observability import device_telemetry as _dt

    def run(roles, warm=True):
        if warm:
            run(roles, warm=False)   # compile cache warm, same shapes
        router = Router(build_replicas(factory, 2, roles=roles,
                                       max_queue=len(work)))
        mark = _dt.COSTS.issued_totals()
        t0 = time.perf_counter()
        handles = [router.submit(p, max_new_tokens=nt if warm else 2)
                   for p, nt in work]
        outs = [h.result(timeout=600) for h in handles]
        dt = time.perf_counter() - t0
        flops = _dt.COSTS.issued_totals()["flops"] - mark["flops"]
        reps = [router.replica(rid) for rid in router.replica_ids]
        if not warm:
            router.shutdown(drain=True, timeout=60)
        return router, reps, outs, dt, flops

    drouter, dreps, douts, ddt, dflops = run(["prefill", "decode"])
    brouter, breps, bouts, bdt, bflops = run(["both", "both"])

    # scheduler ledgers must balance on every replica, with the
    # prefill side's requests terminating as "handoff" (never lost)
    ledgers = {}
    for rep in dreps + breps:
        st = rep.scheduler.stats()
        led = st["requests"]
        ledgers[f"{rep.role}:{rep.replica_id}"] = dict(led)
        assert led["submitted"] == (
            led["completed"] + led["failed"] + led["cancelled"]
            + led["expired"] + led["handoff"] + st["queued"]
            + st["inflight"]), (rep.replica_id, st)

    pre, dec = dreps
    exports = int(pre.engine.handoff_exports)
    assert exports > 0, "disagg run exported no KV handoffs"
    outputs_match = douts == bouts
    assert outputs_match, "disaggregated outputs diverge from baseline"

    def tpot(reps):
        # decode TPOT pooled across the topology's replicas
        import math
        best = {"p50": [], "p99": [], "count": 0}
        for rep in reps:
            snap = rep.registry.snapshot()
            h = snap["pt_serving_tpot_seconds"]
            if h["count"]:
                best["p50"].append((h["p50"], h["count"]))
                best["p99"].append((h["p99"], h["count"]))
                best["count"] += h["count"]
        if not best["count"]:
            return {"p50_s": 0.0, "p99_s": 0.0, "count": 0}
        w50 = sum(p * c for p, c in best["p50"]) / best["count"]
        p99 = max(p for p, _ in best["p99"])
        return {"p50_s": round(w50, 6), "p99_s": round(p99, 6),
                "count": best["count"]}

    d_tpot, b_tpot = tpot(dreps), tpot(breps)
    if on_tpu and b_tpot["count"]:
        # CPU wall-clock is too noisy to gate on; on chip the decode
        # replica's isolation must not cost TPOT tail latency
        assert d_tpot["p99_s"] <= 1.25 * b_tpot["p99_s"], (d_tpot,
                                                           b_tpot)

    # per-role analytic MFU: model FLOPs attributed by what each role
    # actually computed (prefill: prompt tokens; decode: output
    # tokens), over the shared wall clock — the utilization split the
    # role specialization is supposed to show
    from jax import tree_util as _tu
    n_params = sum(int(np.prod(p.shape))
                   for p in _tu.tree_leaves(params))
    pre_toks = int(pre.engine.prefill_tokens)
    dec_toks = sum(len(o) for o in douts)
    role_mfu = {
        "prefill": round(_dt.COSTS.mfu_over(
            2.0 * n_params * pre_toks, ddt), 6),
        "decode": round(_dt.COSTS.mfu_over(
            2.0 * n_params * dec_toks, ddt), 6),
    }

    dsnap = dec.registry.snapshot()
    return {
        "workload": "disagg-mixed",
        "requests": len(work),
        "long_prompts": n_long, "chatty": n_chat,
        "outputs_match": outputs_match,
        "handoff_exports": exports,
        "handoff_imports": int(dec.engine.handoff_imports),
        "handoff_bytes": int(pre.engine.handoff_bytes),
        "handoff_failures": int(pre.engine.handoff_failures
                                + dec.engine.handoff_failures),
        "handoff_p50_s": round(
            dsnap["pt_handoff_seconds"]["p50"], 6)
        if dsnap["pt_handoff_seconds"]["count"] else 0.0,
        "router_handoffs": int(drouter.handoffs.value),
        "decode_tpot": d_tpot,
        "baseline_decode_tpot": b_tpot,
        "disagg_tokens_per_sec": round(
            sum(len(o) for o in douts) / ddt, 1),
        "baseline_tokens_per_sec": round(
            sum(len(o) for o in bouts) / bdt, 1),
        "per_role_mfu": role_mfu,
        "measured_mfu": round(_dt.COSTS.mfu_over(dflops, ddt), 6),
        "ledgers": ledgers,
        "loss": 0.0,
    }


def _bench_serving_fleet(on_tpu, params, cfg, dtype):
    """PT_SERVE_FLEET=1: the multi-host fleet plane. One prefill + one
    decode FleetWorker spawned as real SUBPROCESSES on loopback
    (serving/fleet.py) behind the unchanged Router — RemoteReplica
    satisfies the Replica duck type, so the router code is byte-for-
    byte the single-host router — vs the in-process router at equal
    capacity on the identical seeded mixed workload. Every request
    prefills in one process and decodes in the other, so its KV pages
    cross a real socket; outputs must be token-identical to the
    in-process run, and the artifact reports handoff wire bytes/sec as
    counted by the framing layer (pt_fleet_handoff_wire_bytes), not
    estimated.

    The workers always run the tiny float32 engine on CPU: two child
    processes cannot share the parent's chip, and this bench measures
    the transport plane, not the matmuls. On a TPU host the in-process
    baseline runs on-chip, so token identity is asserted only when the
    parent is CPU too (the comparison is always reported)."""
    import socket

    import jax.numpy as jnp
    from paddle_tpu.models import llama_spmd as M
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving import (FleetPlane, Router, build_replicas,
                                    fleet)

    if on_tpu:
        cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                               kv_heads=2, ffn=128)
        dtype = jnp.float32
        params = M.init_params(cfg, seed=0, dtype=dtype)
    per_seqs, page, max_seq_len = 2, 8, 64
    n_long, n_chat, long_len, chat_len = 3, 3, 24, 4
    long_new, chat_new = 4, 10
    tier_bytes = 8 << 20
    rng = _data_rng()
    long_p = [list(map(int, rng.randint(1, cfg.vocab_size, long_len)))
              for _ in range(n_long)]
    chat_p = [list(map(int, rng.randint(1, cfg.vocab_size, chat_len)))
              for _ in range(n_chat)]
    work = []
    for i in range(max(n_long, n_chat)):
        if i < n_long:
            work.append((long_p[i], long_new))
        if i < n_chat:
            work.append((chat_p[i], chat_new))

    # -- in-process baseline: same topology, same process --------------
    def factory(i):
        return ServingEngine(params, cfg, max_seqs=per_seqs,
                             max_seq_len=max_seq_len, page_size=page,
                             dtype=dtype, prefix_cache=True,
                             host_tier_bytes=tier_bytes,
                             use_pallas=False)

    def run_baseline(warm=True):
        if warm:
            run_baseline(warm=False)   # compile cache warm, same shapes
        router = Router(build_replicas(factory, 2,
                                       roles=["prefill", "decode"],
                                       max_queue=len(work)))
        t0 = time.perf_counter()
        handles = [router.submit(p, max_new_tokens=nt if warm else 2)
                   for p, nt in work]
        outs = [h.result(timeout=600) for h in handles]
        dt = time.perf_counter() - t0
        router.shutdown(drain=True, timeout=60)
        return outs, dt

    bouts, bdt = run_baseline()

    # -- fleet: the same two roles, each in its own process ------------
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    endpoint = f"127.0.0.1:{port}"
    spec = {"master": endpoint, "world_size": 3, "seed": 0,
            "model": vars(cfg), "dtype": "float32",
            "engine": {"max_seqs": per_seqs, "max_seq_len": max_seq_len,
                       "page_size": page, "use_pallas": False,
                       "prefix_cache": True,
                       "host_tier_bytes": tier_bytes},
            "replica": {"max_queue": len(work)}}
    procs = [
        fleet.spawn_worker(dict(spec, name="p0", rank=1, role="prefill",
                                host="hostA"),
                           env={"JAX_PLATFORMS": "cpu"}),
        fleet.spawn_worker(dict(spec, name="d0", rank=2, role="decode",
                                host="hostB"),
                           env={"JAX_PLATFORMS": "cpu"}),
    ]
    plane = router = None
    try:
        plane = FleetPlane(endpoint, ["p0", "d0"])
        router = Router(plane.replicas)
        # warm pass: the children compile their fixed shapes once; the
        # workers persist, so the timed pass reuses the same processes
        for h in [router.submit(p, max_new_tokens=2) for p, _ in work]:
            h.result(timeout=600)
        t0 = time.perf_counter()
        handles = [router.submit(p, max_new_tokens=nt)
                   for p, nt in work]
        fouts = [h.result(timeout=600) for h in handles]
        fdt = time.perf_counter() - t0

        reps = [router.replica(rid) for rid in router.replica_ids]
        ledgers = {}
        for rep in reps:
            st = rep.stats()
            led = st["requests"]
            ledgers[f"{rep.role}:{rep.replica_id}"] = dict(led)
            assert led["submitted"] == (
                led["completed"] + led["failed"] + led["cancelled"]
                + led["expired"] + led["handoff"] + st["queued"]
                + st["inflight"]), (rep.replica_id, st)

        # worker-side counters cross the control plane like everything
        # else; the prefill worker's framing layer counted the handoff
        # payload bytes it actually put on the bulk socket
        pre = next(r for r in reps if r.role == "prefill")
        snap = pre.scheduler.metrics_snapshot()

        def _val(key):
            return int((snap.get(key) or {}).get("value", 0))

        serves = _val("pt_fleet_handoff_serves")
        wire_bytes = _val("pt_fleet_handoff_wire_bytes")
        eng_bytes = _val("pt_handoff_bytes")
        assert serves >= len(work), snap.get("pt_fleet_handoff_serves")
        assert wire_bytes > 0, "no handoff bytes crossed the socket"

        outputs_match = fouts == bouts
        if not on_tpu:
            assert outputs_match, \
                "fleet outputs diverge from the in-process router"
        migrations = int(router.handoffs.value)

        ok = router.shutdown(drain=True, timeout=60)
        codes = [p.wait(timeout=30) for p in procs]
        router = None
        return {
            "workload": "fleet-mixed",
            "requests": len(work),
            "workers": {"p0": "hostA", "d0": "hostB"},
            "outputs_match": outputs_match,
            "handoff_serves": serves,
            "handoff_wire_bytes": wire_bytes,
            "handoff_wire_bytes_per_sec": round(wire_bytes / fdt, 1),
            "handoff_engine_bytes": eng_bytes,
            "router_handoffs": migrations,
            "fleet_tokens_per_sec": round(
                sum(len(o) for o in fouts) / fdt, 1),
            "baseline_tokens_per_sec": round(
                sum(len(o) for o in bouts) / bdt, 1),
            "worker_exit_codes": codes,
            "clean_shutdown": bool(ok) and codes == [0, 0],
            "ledgers": ledgers,
            "step_time_s": round(
                fdt / max(sum(len(o) for o in fouts), 1), 5),
            "loss": 0.0,
        }
    finally:
        if router is not None:
            router.shutdown(drain=False, timeout=5)
        if plane is not None:
            plane.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def _bench_serving_slo(on_tpu, params, cfg, dtype):
    """PT_SERVE_SLO=1: goodput accounting over a mixed interactive +
    batch workload on ONE engine (the contention the SLO plane exists
    to attribute): chatty short prompts tagged `slo="interactive"`
    interleaved with long-prompt `slo="batch"` requests. The artifact
    reads everything off the per-request timeline ledger — goodput
    tokens vs total, attained/violated counts by class, violations
    attributed to their dominant phase, and per-phase latency
    percentiles — the same series /metrics exposes in production."""
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving import RequestScheduler

    if on_tpu:
        max_seqs, page, max_seq_len = 8, 16, 1024
        n_inter, n_batch, chat_len, long_len = 8, 4, 12, 384
        inter_new, batch_new = 48, 12
    else:
        max_seqs, page, max_seq_len = 2, 8, 64
        n_inter, n_batch, chat_len, long_len = 3, 2, 4, 24
        inter_new, batch_new = 8, 4
    rng = _data_rng()
    inter_p = [list(map(int, rng.randint(1, cfg.vocab_size, chat_len)))
               for _ in range(n_inter)]
    batch_p = [list(map(int, rng.randint(1, cfg.vocab_size, long_len)))
               for _ in range(n_batch)]
    # interleave so batch prefill pressure lands while interactive
    # decodes are in flight — the interference SLO attribution is for
    work = []
    for i in range(max(n_inter, n_batch)):
        if i < n_inter:
            work.append((inter_p[i], inter_new, "interactive"))
        if i < n_batch:
            work.append((batch_p[i], batch_new, "batch"))

    engine = ServingEngine(params, cfg, max_seqs=max_seqs,
                           max_seq_len=max_seq_len, page_size=page,
                           dtype=dtype, prefix_cache=True,
                           use_pallas=None if on_tpu else False)
    sched = RequestScheduler(engine, max_queue=len(work) + 1)
    # warm pass (no SLO class): compile outside the timed window
    sched.submit(inter_p[0], max_new_tokens=2).result(timeout=600)
    mark = sched.metrics_snapshot()

    t0 = time.perf_counter()
    handles = [sched.submit(p, max_new_tokens=nt, slo=slo)
               for p, nt, slo in work]
    outs = [h.result(timeout=600) for h in handles]
    dt = time.perf_counter() - t0
    snap = sched.metrics_snapshot()
    sched.shutdown(drain=True, timeout=60)

    def ctr(s, key):
        m = s.get(key)
        return int(m["value"]) if m else 0

    def d_ctr(key):
        return ctr(snap, key) - ctr(mark, key)

    attained, violated_by_phase = {}, {}
    for key in snap:
        if key.startswith("pt_slo_attained{"):
            cls = key.split('slo="', 1)[1].rstrip('"}')
            n = d_ctr(key)
            if n:
                attained[cls] = n
        elif key.startswith("pt_slo_violated{"):
            ph = key.split('phase="', 1)[1].rstrip('"}')
            n = d_ctr(key)
            if n:
                violated_by_phase[ph] = n
    n_attained = sum(attained.values())
    n_violated = sum(violated_by_phase.values())
    total = d_ctr("pt_tokens")
    goodput = d_ctr("pt_goodput_tokens")
    phase_latency = {}
    for ph in ("queued", "prefill", "decode", "preempted", "handoff"):
        h = snap.get(f"pt_phase_{ph}_seconds") or {}
        h0 = mark.get(f"pt_phase_{ph}_seconds") or {}
        phase_latency[ph] = {
            # count deltas the warm pass out; the percentiles come off
            # the whole histogram (one warm sample is bench noise)
            "count": int(h.get("count", 0)) - int(h0.get("count", 0)),
            "p50_s": round(float(h.get("p50", 0.0) or 0.0), 6),
            "p99_s": round(float(h.get("p99", 0.0) or 0.0), 6)}

    assert n_attained + n_violated == len(work), (attained,
                                                  violated_by_phase)
    assert total == sum(len(o) for o in outs), (total, outs)
    return {
        "workload": "slo-goodput",
        "requests": len(work),
        "interactive": n_inter, "batch": n_batch,
        "total_tokens": total,
        "goodput_tokens": goodput,
        "goodput_ratio": round(goodput / total, 6) if total else 0.0,
        "slo_attained": attained,
        "slo_violated": n_violated,
        "violations_by_phase": violated_by_phase,
        "phase_latency": phase_latency,
        "step_anomalies": d_ctr("pt_step_anomalies"),
        "tokens_per_sec": round(total / dt, 1) if dt else 0.0,
        "loss": 0.0,
    }


def _bench_serving_pulse(on_tpu, params, cfg, dtype):
    """PT_SERVE_PULSE=1 (bench mode): the telemetry pulse plane smoke
    (ISSUE 15). One pipelined-pump engine runs a decode workload under
    a seeded `FaultPlan` that delays a single device-step launch well
    past the anomaly sentinel's band; the pulse plane (sampling at a
    tight bench interval) must show the stall as a spike in the
    step-time ring and write EXACTLY ONE capture bundle (the min-
    interval rate limit swallows any repeat triggers). The artifact
    also times the sampler's full tick — scan + registry snapshot +
    ring folds + trigger check — against the live registry, the cost
    every scrape and pulse-thread pass pays; it must stay bounded."""
    import statistics
    import tempfile
    from paddle_tpu.models.llama_serving import ServingEngine
    from paddle_tpu.serving import FaultPlan, MetricsRegistry, \
        RequestScheduler

    if on_tpu:
        max_seqs, new_tok, nreq = 8, 64, 8
        max_seq_len, page = 512, 16
        fault_spec = "step_launch:delay@40:delay=0.5"
    else:
        max_seqs, new_tok, nreq = 4, 48, 4
        max_seq_len, page = 128, 8
        fault_spec = "step_launch:delay@30:delay=0.5"
    rng = _data_rng()
    prompts = [list(map(int, rng.randint(
        1, cfg.vocab_size, 16 if on_tpu else 4))) for _ in range(nreq)]

    def make(faults=None):
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            dtype=dtype, prefix_cache=True,
                            use_pallas=None if on_tpu else False,
                            faults=FaultPlan(faults) if faults else None)
        return RequestScheduler(eng, max_queue=nreq + 1,
                                metrics=MetricsRegistry(),
                                pipeline=True)

    cap_dir = tempfile.mkdtemp(prefix="pt_pulse_bench_")
    knobs = {"PT_PULSE_INTERVAL_S": "0.05", "PT_CAPTURE_DIR": cap_dir,
             "PT_CAPTURE_MIN_S": "600", "PT_CAPTURE_MAX": "8"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        # warm the compile caches first: the drill's early steps must
        # be real decode steps, not XLA compiles, so the sentinel's
        # baseline has settled before the injected stall lands
        warm = make()
        warm.submit(prompts[0], max_new_tokens=2).result(timeout=600)
        warm.shutdown(drain=True, timeout=60)

        sched = make(fault_spec)
        plane = sched._pulse
        assert plane is not None and plane.thread_alive, \
            "pulse plane must be live in bench mode"
        t0 = time.perf_counter()
        handles = [sched.submit(p, max_new_tokens=new_tok)
                   for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        dt = time.perf_counter() - t0
        # deterministic final pass: drain the sentinel, judge triggers,
        # land the bundle before any assert reads the plane's state
        plane.tick()
        # sampler self-cost: K full ticks against the now-populated
        # registry (the per-scrape overhead the plane adds)
        costs = []
        for _ in range(20):
            c0 = time.perf_counter()
            plane.tick()
            costs.append(time.perf_counter() - c0)
        payload = sched.pulse()
        scrape_self = sched.metrics_snapshot().get(
            "pt_scrape_self_seconds") or {}
        sched.shutdown(drain=True, timeout=60)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    series = payload["signals"].get("pt_serving_step_seconds:p99") or []
    vals = [v for _, v in series if v]
    med = statistics.median(vals) if vals else 0.0
    spike = round(max(vals) / med, 2) if med > 0 else 0.0
    tick_mean = statistics.mean(costs)
    bundles = sorted(d for d in os.listdir(cap_dir)
                     if d.startswith("bundle-"))
    total = sum(len(o) for o in outs)

    assert payload["enabled"], payload
    assert payload["triggers"]["step_stall"] >= 1, payload["triggers"]
    assert len(bundles) == 1, bundles   # rate limit: one, not a storm
    with open(os.path.join(cap_dir, bundles[0], "meta.json")) as f:
        meta = json.load(f)
    assert meta["trigger"] == "step_stall", meta
    # bounded: a full tick over this registry is sub-millisecond work;
    # 25ms leaves slack for a loaded CI box while still catching a
    # device sync (a TPU round trip alone would blow through it)
    assert tick_mean < 0.025, f"pulse tick mean {tick_mean:.4f}s"
    return {
        "workload": "pulse-plane",
        "requests": nreq, "batch": max_seqs,
        "fault_plan": fault_spec,
        "signals": len(payload["signals"]),
        "step_p99_spike_x": spike,
        "stall_triggers": payload["triggers"]["step_stall"],
        "bundles_written": len(bundles),
        "bundle_trigger": meta["trigger"],
        "bundle_trace_ids": len(meta.get("trace_ids") or []),
        "tick_mean_ms": round(tick_mean * 1e3, 3),
        "tick_p99_ms": round(sorted(costs)[-1] * 1e3, 3),
        "scrape_self_ms": round(
            float(scrape_self.get("value", 0.0)) * 1e3, 3),
        "tokens_per_sec": round(total / dt, 1) if dt else 0.0,
        "loss": 0.0,
    }


def _bench_serving_multiturn(on_tpu, params, cfg, dtype):
    """PT_SERVE_MULTITURN=1: the KV-cache tiering workload. N chat
    conversations run a first turn, a burst of distinct prompts then
    thrashes the device prefix cache (every conversation's parked
    pages get evicted — and, with the tier on, spilled to host RAM),
    and finally every conversation RETURNS with its history as the
    prompt. With the tier the returning turn restores its prefix from
    host memory and prefills only the new tokens; the baseline is the
    IDENTICAL workload with the tier off (evictions discard), which
    must produce token-identical outputs while re-prefilling whole
    histories. The artifact carries the tier ledger (hit rate, spills,
    tokens reused) and both sides' returning-phase prefill tokens —
    the capacity the host tier buys, measured not claimed."""
    from paddle_tpu.models.llama_serving import Request, ServingEngine

    if on_tpu:
        max_seqs, page, max_seq_len, num_pages = 4, 16, 512, 129
        convs, burst, new_tok = 8, 16, 32
        t1_len, b_len, t2_extra = 64, 128, 16
        tier_bytes = 256 << 20
    else:
        max_seqs, page, max_seq_len, num_pages = 2, 8, 64, 11
        convs, burst, new_tok = 3, 6, 6
        t1_len, b_len, t2_extra = 12, 17, 4
        tier_bytes = 8 << 20
    rng = _data_rng()
    # distinct leading token per prompt: conversations and burst
    # traffic must never share a block-aligned prefix, or the burst
    # would HIT the cache instead of thrashing it
    t1_prompts = [[2 * i + 1] + list(map(int, rng.randint(
        1, cfg.vocab_size, t1_len - 1))) for i in range(convs)]
    burst_prompts = [[2 * (convs + j) + 1] + list(map(int, rng.randint(
        1, cfg.vocab_size, b_len - 1))) for j in range(burst)]
    extras = [list(map(int, rng.randint(1, cfg.vocab_size, t2_extra)))
              for _ in range(convs)]

    def run(hb, warm=True):
        # warm each config's own compile set with a FULL replay: the
        # returning turn's suffix-prefill bucket depends on how many
        # tokens are cached, so only an identical trajectory warms the
        # exact shapes the timed phase hits (a short warm pass would
        # leave a fresh XLA compile inside the timed region)
        nt = new_tok
        if warm:
            run(hb, warm=False)
        eng = ServingEngine(params, cfg, max_seqs=max_seqs,
                            max_seq_len=max_seq_len, page_size=page,
                            num_pages=num_pages, dtype=dtype,
                            prefix_cache=True, host_tier_bytes=hb,
                            use_pallas=None if on_tpu else False)
        outs = {}
        for i, p in enumerate(t1_prompts):
            eng.submit(Request(f"c{i}", p, max_new_tokens=nt))
        for r in eng.run():
            outs[r.rid] = list(r.output)
        # the burst: one at a time, so parking pressure accumulates
        # and the LRU actually churns through every parked page
        for j, p in enumerate(burst_prompts):
            eng.submit(Request(f"b{j}", p, max_new_tokens=nt))
            eng.run()
        eng.host_tier.flush(timeout=120)
        pt0 = eng.prefill_tokens
        t2 = [t1_prompts[i] + outs[f"c{i}"] + extras[i]
              for i in range(convs)]
        t0 = time.perf_counter()
        for i, p in enumerate(t2):
            eng.submit(Request(f"t2-{i}", p, max_new_tokens=nt))
        done = eng.run()
        dt = time.perf_counter() - t0
        for r in done:
            outs[r.rid] = list(r.output)
        t2_tokens = sum(len(outs[f"t2-{i}"]) for i in range(convs))
        return eng, outs, eng.prefill_tokens - pt0, t2_tokens, dt

    beng, bouts, bprefill, btok, bdt = run(0)           # tier off
    teng, touts, tprefill, ttok, tdt = run(tier_bytes)  # tier on
    tier = teng.host_tier.stats()
    return {
        "workload": "multi-turn",
        "conversations": convs, "burst_requests": burst,
        "outputs_match": touts == bouts,
        "tier_hit_rate": round(tier["hit_rate"], 3),
        "tier_spills": tier["spills"],
        "tier_drops": tier["drops"],
        "tokens_reused": tier["tokens_reused"],
        "tier_restores": tier["restores"],
        "tier_host_bytes": tier["host_bytes"],
        "tier_pages": tier["pages"],
        # the headline: returning conversations' prefill compute with
        # and without the tier, at equal (token-identical) outputs
        "returning_prefill_tokens": tprefill,
        "baseline_prefill_tokens": bprefill,
        "prefill_tokens_saved": bprefill - tprefill,
        "returning_tokens_per_sec": round(ttok / tdt, 1),
        "baseline_returning_tokens_per_sec": round(btok / bdt, 1),
        "prefix_evictions": int(teng.prefix_cache.evictions),
        "loss": 0.0,
    }


def bench_serving_load(on_tpu):
    """Serving under load (VERDICT r4 item 4): Poisson arrivals, real
    concurrency, TTFT/TPOT percentiles and preemption counts, swept
    over {fp32, int8 KV} x {spec on, off}. The reference stack
    publishes throughput/latency for its block-attention serving; this
    is the comparable artifact. Knobs scale by backend: CPU runs a
    scaled-down shadow of the TPU workload (PT_BENCH_LOAD_REQS
    overrides the request count)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_spmd as M
    from paddle_tpu.models.llama_serving import Request, ServingEngine

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        nreq = int(os.environ.get("PT_BENCH_LOAD_REQS", "64"))
        max_seqs, dtype, max_seq_len, page = 8, jnp.bfloat16, 1536, 16
        plo, phi, nlo, nhi = 128, 1024, 64, 256
        rate = 2.0       # requests/s Poisson arrivals
    else:
        cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                               kv_heads=2, ffn=128)
        nreq = int(os.environ.get("PT_BENCH_LOAD_REQS", "24"))
        max_seqs, dtype, max_seq_len, page = 4, jnp.float32, 128, 8
        plo, phi, nlo, nhi = 8, 48, 8, 32
        rate = 40.0
    params = M.init_params(cfg, seed=0, dtype=dtype)

    rng = _data_rng()
    arrivals = np.cumsum(rng.exponential(1.0 / rate, nreq))
    reqs = []
    for i in range(nreq):
        plen = int(rng.randint(plo, phi + 1))
        if rng.rand() < 0.5:   # half the traffic is repetitive (spec-able)
            motif = list(map(int, rng.randint(1, cfg.vocab_size, 3)))
            prompt = (motif * (plen // 3 + 1))[:plen]
        else:
            prompt = list(map(int, rng.randint(1, cfg.vocab_size, plen)))
        reqs.append((arrivals[i], prompt, int(rng.randint(nlo, nhi + 1))))

    def make_engine(cache_dtype, spec):
        # pool oversubscribed ~40% vs worst-case concurrent demand so
        # the preemption path shows up in the numbers
        return ServingEngine(params, cfg, max_seqs=max_seqs,
                             max_seq_len=max_seq_len, page_size=page,
                             dtype=dtype, cache_dtype=cache_dtype,
                             spec_decode=spec,
                             num_pages=max(max_seqs * (max_seq_len // page)
                                           // 3, max_seq_len // page + 1))

    def warm_prefill_buckets():
        # prefill_varlen compiles per power-of-2 token bucket and is
        # config-independent; whichever config runs first would
        # otherwise eat every bucket compile inside its timed run
        # (observed: fp TTFT 20x worse than the identical-capacity spec
        # config, purely compile skew). Admission rounds batch up to
        # max_seqs prompts, so buckets reach pow2(max_seqs * phi).
        import math as _m
        weng = make_engine(None, 0)
        b = page
        top = 1 << _m.ceil(_m.log2(max_seqs * phi))
        while b <= top:
            # batched round -> prefill_varlen bucket; single round ->
            # the monolithic prefill path (take==1 admissions)
            plen = max(min(b // max_seqs, max_seq_len - 2), 1)
            for i in range(max_seqs):
                weng.submit(Request(f"wb{b}_{i}",
                                    list(rng.randint(1, cfg.vocab_size,
                                                     plen)),
                                    max_new_tokens=1))
            weng.run()
            p1 = max(min(b - 1, max_seq_len - 2), 1)
            weng.submit(Request(f"ws{b}",
                                list(rng.randint(1, cfg.vocab_size, p1)),
                                max_new_tokens=1))
            weng.run()
            b *= 2

    def run_cfg(cache_dtype, spec):
        # warm THIS config's decode/verify compiles before the arrival
        # clock starts (prefill buckets are pre-warmed globally)
        weng = make_engine(cache_dtype, spec)
        for i, (_, prompt, _n) in enumerate(reqs[:max_seqs]):
            weng.submit(Request(f"w{i}", prompt,
                                max_new_tokens=max(2 * max(spec, 1), 4)))
        weng.run()
        eng = make_engine(cache_dtype, spec)
        t0 = time.perf_counter()
        first_tok = {}
        done_at = {}
        pending = list(enumerate(reqs))
        while pending or any(s is not None for s in eng._slots) \
                or eng._waiting:
            now = time.perf_counter() - t0
            while pending and pending[0][1][0] <= now:
                i, (_, prompt, n_new) = pending.pop(0)
                eng.submit(Request(i, prompt, max_new_tokens=n_new))
            if not eng.step():
                if pending:   # idle gap before the next arrival
                    time.sleep(min(pending[0][1][0] - now, 0.01))
                continue
            now = time.perf_counter() - t0
            for r in list(eng.finished):
                if r.rid not in done_at:
                    done_at[r.rid] = now
            for s in eng._slots:
                if s is not None and s.output and s.rid not in first_tok:
                    first_tok[s.rid] = now
        wall = time.perf_counter() - t0
        for r in eng.finished:   # first token may have landed at finish
            first_tok.setdefault(r.rid, done_at[r.rid])
        ttft = np.asarray([first_tok[i] - reqs[i][0] for i in range(nreq)])
        tpot = np.asarray(
            [(done_at[i] - first_tok[i]) / max(len(r.output) - 1, 1)
             for i, r in ((r.rid, r) for r in eng.finished)])
        total_new = sum(len(r.output) for r in eng.finished)
        return {
            "tokens_per_sec": round(total_new / wall, 1),
            "ttft_p50_ms": round(float(np.percentile(ttft, 50)) * 1e3, 1),
            "ttft_p99_ms": round(float(np.percentile(ttft, 99)) * 1e3, 1),
            "tpot_p50_ms": round(float(np.percentile(tpot, 50)) * 1e3, 2),
            "tpot_p99_ms": round(float(np.percentile(tpot, 99)) * 1e3, 2),
            "preemptions": eng.preemptions,
            "new_tokens": total_new,
        }

    warm_prefill_buckets()
    table = {}
    for name, (cd, sp) in {
        "fp": (None, 0), "fp_spec": (None, 4),
        "int8": ("int8", 0), "int8_spec": ("int8", 4),
    }.items():
        table[name] = run_cfg(cd, sp)
    base = table["fp"]
    return {"decode_tokens_per_sec": base["tokens_per_sec"],
            "requests": nreq, "batch": max_seqs,
            "arrival_rate_per_s": rate,
            "prompt_tokens": [plo, phi], "new_tokens_range": [nlo, nhi],
            "step_time_s": round(1.0 / max(base["tokens_per_sec"], 1e-9), 5),
            "loss": 0.0, "configs": table}


def bench_input(on_tpu):
    """Input-bound ResNet (VERDICT r3 item 7): real JPEG files on disk,
    decoded by DataLoader process workers, racing the model step. The
    headline number is the feed ratio: host decode throughput / model
    consumption rate — >= 1 means the input pipeline keeps a chip fed.
    Reference: python/paddle/io/dataloader/dataloader_iter.py:368."""
    import shutil
    import tempfile
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.io import DataLoader
    from paddle_tpu.parallel.trainer import Trainer
    from paddle_tpu.vision.datasets import DatasetFolder
    from paddle_tpu.vision._codec import encode_jpeg_np

    bs, size, iters, n_img = (64, 224, 5, 512) if on_tpu else (8, 64, 2, 64)
    root = tempfile.mkdtemp(prefix="pt_jpeg_bench_")
    try:
        rng = _data_rng()
        for cls in range(4):
            cdir = os.path.join(root, f"class{cls}")
            os.makedirs(cdir)
            for i in range(n_img // 4):
                img = rng.randint(0, 255, (size, size, 3), np.uint8)
                with open(os.path.join(cdir, f"{i}.jpg"), "wb") as f:
                    f.write(encode_jpeg_np(img, quality=85))

        def tf(img):
            x = np.asarray(img, np.float32).transpose(2, 0, 1) / 255.0
            return (x - 0.45) / 0.22

        ds = DatasetFolder(root, transform=tf)
        loader = DataLoader(ds, batch_size=bs, shuffle=True, num_workers=2,
                            drop_last=True)
        # host decode throughput (workers overlap decode with iteration)
        t0 = time.perf_counter()
        n = 0
        for xb, yb in loader:
            n += len(yb)
        decode_dt = time.perf_counter() - t0
        imgs_per_sec_host = n / decode_dt

        model = pt.vision.models.resnet18(num_classes=4)
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
        ce = pt.nn.CrossEntropyLoss()

        def loss_fn(m, b):
            x, y = b
            return ce(m(x).astype("float32"), y)

        tr = Trainer(model, opt, loss_fn, mesh=_mesh1())
        xb0 = np.ascontiguousarray(xb[:bs]).astype(
            np.float32 if not on_tpu else jnp.bfloat16)
        yb0 = np.asarray(yb[:bs], np.int64)
        dt, loss = _time_steps(tr, (xb0, yb0), iters)
        model_imgs_per_sec = bs / dt
        return {"imgs_per_sec_host_decode": round(imgs_per_sec_host, 1),
                "imgs_per_sec_model": round(model_imgs_per_sec, 1),
                "feed_ratio": round(imgs_per_sec_host /
                                    model_imgs_per_sec, 3),
                "n_images": n, "batch": bs,
                "step_time_s": round(dt, 4), "loss": loss}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_dlrm(on_tpu):
    """DLRM rec-sys train step: host PS pull/push racing the jitted
    dense tower (reference: PaddleRec on the_one_ps). The number to
    watch is examples/sec with the PS round-trip included."""
    from paddle_tpu.distributed.ps import PSClient, SparseTable
    from paddle_tpu.models.dlrm import DLRMConfig, DLRMTrainer

    if on_tpu:
        cfg = DLRMConfig(emb_dim=64, n_sparse=26, dense_dim=13,
                         bottom=(512, 256), top=(512, 256))
        bs, iters, vocab, shards = 4096, 10, 1_000_000, 4
    else:
        cfg = DLRMConfig(emb_dim=8, n_sparse=4, dense_dim=5, bottom=(16,),
                         top=(16,))
        bs, iters, vocab, shards = 128, 3, 1000, 2
    rng = _data_rng()

    def batch():
        ids = rng.randint(0, vocab, (bs, cfg.n_sparse)).astype(np.int64)
        ids += np.arange(cfg.n_sparse, dtype=np.int64)[None] * (vocab * 2 + 1)
        dense = rng.randn(bs, cfg.dense_dim).astype(np.float32)
        y = (rng.rand(bs) > 0.7).astype(np.float32)
        return ids, dense, y

    def run_shards(n):
        client = PSClient([SparseTable(cfg.emb_dim, optimizer="adagrad",
                                       lr=0.05, seed=s) for s in range(n)])
        tr = DLRMTrainer(cfg, client, seed=0, lr=0.05)
        loss = tr.train_step(*batch())     # warmup/compile
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = tr.train_step(*batch())
        dt = (time.perf_counter() - t0) / iters
        return dt, loss, len(client)

    # scaling curve over shard counts (VERDICT r4 weak #6: a single
    # shard count demonstrates the path runs, not how the PS fan-out
    # scales); headline = the default count
    sweep = {}
    dt = loss = nrows = None
    for n in sorted({1, shards, shards * 2}):
        dt_n, loss_n, nrows_n = run_shards(n)
        sweep[str(n)] = round(bs / dt_n, 1)
        if n == shards:   # the sweep already measured the headline run
            dt, loss, nrows = dt_n, loss_n, nrows_n
    return {"examples_per_sec": round(bs / dt, 1), "batch": bs,
            "rows_materialized": nrows, "shards": shards,
            "examples_per_sec_by_shards": sweep,
            "step_time_s": round(dt, 4), "loss": float(loss)}


BENCHES = {"resnet50": bench_resnet50, "bert": bench_bert, "moe": bench_moe,
           "serving": bench_serving, "serving_load": bench_serving_load,
           "input": bench_input, "dlrm": bench_dlrm}


def main():
    import jax
    if os.environ.get("PT_BENCH_CPU") == "1" or not _tpu_alive():
        print("# TPU unreachable; CPU smoke shapes", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    on_tpu = backend != "cpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") if on_tpu else "cpu"

    which = sys.argv[1:] or list(BENCHES)
    here = os.path.dirname(os.path.abspath(__file__))
    for name in which:
        res = BENCHES[name](on_tpu)
        kind = "decode" if name == "serving" else "train step"
        entry = {"metric": f"{name} {kind} ({gen})", "model": name,
                 "unit": "steps/s",
                 "value": round(1.0 / res["step_time_s"], 3),
                 "extra": dict(res, backend=backend)}
        print(json.dumps(entry))
        try:
            with open(os.path.join(here, "BENCH_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps(dict(entry, ts=time.time())) + "\n")
        except OSError:
            pass


if __name__ == "__main__":
    main()
