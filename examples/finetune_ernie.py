"""ERNIE finetune — paddle-style classification recipe on TPU.

    python examples/finetune_ernie.py --steps 30
    python examples/finetune_ernie.py --compiled   # jitted Trainer path

Shows: the ERNIE model family, a varlen token corpus packed through the
C++ libptio .ptvr pipeline, the legacy reader facade, and both the eager
tape loop and the compiled Trainer over the same model.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# NB: pin CPU via jax.config, NOT the JAX_PLATFORMS env var — the env var
# wedges the axon TPU tunnel shim during backend init (see
# __graft_entry__.dryrun_multichip).

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=48)
    ap.add_argument("--compiled", action="store_true",
                    help="use the jitted Trainer instead of the eager tape")
    args = ap.parse_args()

    import jax
    if os.environ.get("PT_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu.models.ernie import (ErnieConfig,
                                         ErnieForSequenceClassification)
    from paddle_tpu.io import native

    pt.seed(0)
    cfg = ErnieConfig.tiny()
    model = ErnieForSequenceClassification(cfg, num_classes=2)
    opt = pt.optimizer.AdamW(learning_rate=5e-4,
                             parameters=model.parameters())
    ce = pt.nn.CrossEntropyLoss()

    # --- synthetic "sentiment" corpus: class k uses token band k --------
    rng = np.random.RandomState(0)
    seqs, labels = [], []
    for i in range(256):
        lab = i % 2
        lo, hi = (1, cfg.vocab_size // 2) if lab == 0 else \
            (cfg.vocab_size // 2, cfg.vocab_size)
        n = rng.randint(8, args.seq)
        seqs.append(rng.randint(lo, hi, n).astype(np.int32))
        labels.append(lab)

    # varlen corpus through the native C++ pipeline, padded per batch
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "corpus.ptvr")
        native.write_varlen_records(path, seqs)
        ds = native.VarlenRecordDataset(path)
        loader = native.NativeVarlenLoader(
            ds, batch_size=args.batch, shuffle=True, seed=1,
            decode=lambda b: np.frombuffer(b, np.int32))
        label_by_key = {s.tobytes(): l for s, l in zip(seqs, labels)}

        def batches():
            while True:
                for recs in loader:
                    # position 0 is a fixed [CLS]=0 anchor the pooler reads
                    ids = np.zeros((len(recs), args.seq), np.int64)
                    for j, r in enumerate(recs):
                        n = min(len(r), args.seq - 1)
                        ids[j, 1:1 + n] = r[:n]
                    ys = np.asarray([label_by_key[r.tobytes()]
                                     for r in recs])
                    yield ids, ys

        it = batches()
        if args.compiled:
            from jax.sharding import Mesh, PartitionSpec as P
            mesh = Mesh(np.asarray(jax.devices()), ("dp",))
            from paddle_tpu.parallel.trainer import Trainer
            tr = Trainer(model, opt, lambda m, b: ce(m(b[0]), b[1]),
                         mesh=mesh, batch_spec=(P("dp"), P("dp")))
            for step in range(args.steps):
                ids, ys = next(it)
                loss = tr.step((ids, ys))
                if step % 5 == 0 or step == args.steps - 1:
                    print(f"[trainer] step {step:3d} "
                          f"loss {float(np.asarray(loss)):.4f}")
            tr.sync_model()
        else:
            for step in range(args.steps):
                ids, ys = next(it)
                loss = ce(model(pt.to_tensor(ids)), pt.to_tensor(ys))
                loss.backward()
                opt.step()
                opt.clear_grad()
                if step % 5 == 0 or step == args.steps - 1:
                    print(f"[eager]   step {step:3d} "
                          f"loss {float(loss.numpy()):.4f}")

    # quick eval on fresh samples
    model.eval()
    ids = np.zeros((64, args.seq), np.int64)
    ys = np.zeros(64, np.int64)
    for i in range(64):
        lab = i % 2
        lo, hi = (1, cfg.vocab_size // 2) if lab == 0 else \
            (cfg.vocab_size // 2, cfg.vocab_size)
        n = rng.randint(8, args.seq - 1)
        ids[i, 1:1 + n] = rng.randint(lo, hi, n)
        ys[i] = lab
    pred = model(pt.to_tensor(ids)).numpy().argmax(-1)
    print(f"eval accuracy: {(pred == ys).mean():.2%}")


if __name__ == "__main__":
    main()
