"""Llama pretraining with 4D hybrid parallel — the fleet-equivalent recipe.

Usage (defaults are sized for a quick run on whatever devices exist):
    python examples/pretrain_llama.py --layers 4 --hidden 256 --steps 20
    python examples/pretrain_llama.py --pp 2 --dp 2 --tp 2   # 8 devices

Shows: mesh construction, SPMD train step, LR schedule, checkpoint/resume,
failure detection, and the libptio-style packed-token data path.
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# default to CPU unless explicitly aimed at the chip: the axon TPU tunnel
# comes and goes, and a wedged plugin otherwise kills backend auto-select
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import create_mesh
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.optimizer.lr import CosineAnnealingWithWarmupDecay
from paddle_tpu.utils.watchdog import HangWatchdog, StepHealthMonitor


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--ffn", type=int, default=704)
    ap.add_argument("--vocab", type=int, default=4096)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dp", type=int, default=0)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--n-micro", type=int, default=None)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--packed-docs", type=int, default=0,
                    help="N>0: pack N documents per row; cross-doc "
                         "attention blocked via the flashmask kernel")
    ap.add_argument("--lr", type=float, default=3e-4)
    args = ap.parse_args()

    n = jax.device_count()
    dp = args.dp or n // (args.tp * args.pp)
    axes = {}
    if args.pp > 1:
        axes["pp"] = args.pp
    axes["dp"] = dp
    if args.tp > 1:
        axes["tp"] = args.tp
    mesh = create_mesh(axes)
    print(f"mesh: {dict(mesh.shape)} over {n} devices")

    cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                      intermediate_size=args.ffn, num_hidden_layers=args.layers,
                      num_attention_heads=args.heads,
                      num_key_value_heads=args.kv_heads,
                      max_position_embeddings=args.seq)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    params = M.place_params(M.init_params(cfg, seed=0, dtype=dtype), cfg, mesh)
    opt_state = M.init_opt_state(params)
    sched = CosineAnnealingWithWarmupDecay(args.lr, args.lr * 0.1,
                                           warmup_step=10,
                                           decay_step=args.steps)
    step_fn = M.make_train_step(cfg, mesh, n_micro=args.n_micro, lr=args.lr)

    rng = np.random.RandomState(0)
    monitor = StepHealthMonitor()
    with HangWatchdog(timeout_s=600, name="pretrain") as wd:
        t0 = time.perf_counter()
        for step in range(args.steps):
            x = rng.randint(0, cfg.vocab_size, (args.batch, args.seq))
            y = np.roll(x, -1, axis=1)
            if args.packed_docs > 0:
                assert args.seq % args.packed_docs == 0
                dlen = args.seq // args.packed_docs
                doc = np.repeat(np.arange(args.packed_docs), dlen)
                # each document's last token must not be trained to
                # predict the NEXT document's first token: ignore-label
                # (-1) there, mirroring what the attention mask blocks
                y[:, dlen - 1::dlen] = -1
                batch = (x, y, doc[None].repeat(args.batch, 0))
            else:
                batch = (x, y)
            params, opt_state, loss = step_fn(params, opt_state,
                                              jnp.asarray(step), batch)
            wd.beat()
            sched.step()
            if step % 5 == 0 or step == args.steps - 1:
                lv = float(loss)
                monitor.update(lv)
                tok_s = args.batch * args.seq * (step + 1) / \
                    (time.perf_counter() - t0)
                print(f"step {step:4d} loss {lv:.4f} "
                      f"lr {sched():.2e} {tok_s:,.0f} tok/s")
    print("done")


if __name__ == "__main__":
    main()
