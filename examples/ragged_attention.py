"""Packed ragged-batch attention with memory_efficient_attention.

    python examples/ragged_attention.py

Shows: documents of different lengths packed into ONE attention call
through the xformers-style BlockDiagonalCausalMask — the bias TYPE
routes to the varlen segment-id pallas kernel (no padding, no O(S^2)
mask), and split() recovers the per-document outputs. This is the
eager/offline face of the same masking the serving engine runs
compiled (reference: python/paddle/incubate/nn/
memory_efficient_attention.py).
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

# default to CPU unless explicitly aimed at the chip: the axon TPU
# tunnel comes and goes, and a wedged plugin otherwise hangs backend
# auto-select (PT_EXAMPLE_TPU=1 to run on hardware)
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu.incubate.nn.attn_bias import BlockDiagonalMask
from paddle_tpu.incubate.nn.memory_efficient_attention import (
    memory_efficient_attention,
)


def main():
    pt.seed(0)
    h, d = 4, 32
    # three "documents" with very different lengths — a ragged batch
    docs = [pt.randn([1, n, h, d]) for n in (37, 128, 9)]

    # pack them once; the mask carries the boundaries
    mask, packed = BlockDiagonalMask.from_tensor_list(docs)
    causal = mask.make_causal()

    out = memory_efficient_attention(packed, packed, packed,
                                     attn_bias=causal)
    outs = mask.split(out)
    for i, (doc, o) in enumerate(zip(docs, outs)):
        print(f"doc {i}: in {list(doc.shape)} -> out {list(o.shape)}")

    # proof of isolation: a document attending alone gives the SAME
    # output as inside the packed batch (no cross-document leakage)
    solo_mask = BlockDiagonalMask.from_seqlens([docs[0].shape[1]])
    solo = memory_efficient_attention(docs[0], docs[0], docs[0],
                                      attn_bias=solo_mask.make_causal())
    err = float(np.abs(outs[0].numpy() - solo.numpy()).max())
    print(f"packed-vs-solo max err: {err:.2e} (isolation holds)")
    assert err < 1e-4


if __name__ == "__main__":
    main()
