"""Named-worker RPC: a two-process control plane.

    python examples/rpc_workers.py

Shows: paddle_tpu.distributed.rpc (reference paddle.distributed.rpc) —
rank 0 spawns rank 1, both rendezvous at a master TCP store, and the
driver farms Python work (here: tokenization-ish string chores and a
numpy reduction) to the worker by NAME, sync and async. This is the
host-side control plane; device compute stays on the SPMD path.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu.distributed.rpc as rpc  # noqa: E402


def chunk_lengths(texts):
    return [len(t.split()) for t in texts]


def square_sum(n):
    return sum(i * i for i in range(n))


def main():
    if os.environ.get("RPC_RANK") == "1":
        rpc.init_rpc("worker", rank=1, world_size=2)
        rpc.shutdown()          # serves until the driver's barrier
        return

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, RPC_RANK="1",
               PADDLE_MASTER_ENDPOINT=f"127.0.0.1:{port}")
    worker = subprocess.Popen([sys.executable, __file__], env=env)
    try:
        rpc.init_rpc("driver", rank=0, world_size=2,
                     master_endpoint=f"127.0.0.1:{port}")
        print("workers:", [i.name for i in rpc.get_all_worker_infos()])
        out = rpc.rpc_sync("worker", chunk_lengths,
                           args=(["to the moon", "paddle on tpu"],))
        print("chunk_lengths on worker ->", out)
        futs = [rpc.rpc_async("worker", square_sum, args=(n,))
                for n in (10, 100, 1000)]
        print("square sums ->", [f.wait() for f in futs])
        rpc.shutdown()
        worker.wait(timeout=60)
    finally:
        # a driver-side failure must not mask the real error with a
        # wait timeout, nor orphan the worker in its 900s rendezvous
        if worker.poll() is None:
            worker.kill()
            worker.wait()
    print("done")


if __name__ == "__main__":
    main()
