"""Production serving runtime over the continuous-batching engine:
HTTP frontend + scheduler + metrics (paddle_tpu.serving).

    python examples/serve_llama.py                  # demo: serve + drive
    python examples/serve_llama.py --port 8000 --forever   # stay up
    python examples/serve_llama.py --spec 4 --cache int8

The demo starts the server, drives it with the stdlib client — a
blocking completion, a streamed one, a burst that exercises queueing —
prints the metrics the run produced, and shuts down gracefully
(in-flight requests drain). The wire protocol is tokenizer-free:
prompts and completions are token-id lists (docs/serving.md).
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# default to CPU unless explicitly aimed at the chip: the axon TPU tunnel
# comes and goes, and a wedged plugin otherwise kills backend auto-select
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import argparse
import threading

import numpy as np

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import ServingEngine
from paddle_tpu.serving import RequestScheduler, ServingClient, ServingServer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--forever", action="store_true",
                    help="serve until Ctrl-C instead of running the demo")
    ap.add_argument("--max-seqs", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--spec", type=int, default=0,
                    help="speculative chunk width G (0 = plain decode)")
    ap.add_argument("--cache", choices=["fp", "int8"], default="fp")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="share KV pages across prompts with a common "
                         "prefix; admissions prefill only their suffix")
    ap.add_argument("--host-tier-mb", type=int, default=0,
                    help="MB of host RAM for the KV spill tier: prefix-"
                         "cache evictions demote pages to host memory "
                         "instead of discarding them (implies "
                         "--prefix-cache); 0 disables")
    ap.add_argument("--pipeline", action="store_true",
                    help="double-buffered pump: launch device step N+1 "
                         "before consuming step N (device-side sampling "
                         "makes the carry possible); token-identical to "
                         "the synchronous pump. PT_SERVE_PIPELINE=1 is "
                         "the env spelling")
    ap.add_argument("--replicas", type=int, default=0,
                    help="N>1: router mode — N independent engine "
                         "replicas behind the prefix-affinity router "
                         "(health-aware failover, per-replica /metrics "
                         "labels); implies --prefix-cache per replica")
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    help="seeded fault plan for chaos drills, e.g. "
                         "'step_launch:raise@4' (docs/reliability.md "
                         "has the grammar); PT_FAULTS is the env "
                         "spelling. Crashed steps warm-restart the "
                         "engine and requeue unstreamed requests")
    args = ap.parse_args()

    cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=8,
                           kv_heads=4, ffn=256, seq=256)
    params = M.init_params(cfg, seed=0)

    def make_engine(_i=0):
        from paddle_tpu.serving import FaultPlan
        return ServingEngine(
            params, cfg, max_seqs=args.max_seqs, max_seq_len=256,
            page_size=16,
            cache_dtype="int8" if args.cache == "int8" else None,
            spec_decode=args.spec,
            prefix_cache=(args.prefix_cache or args.replicas > 1
                          or args.host_tier_mb > 0),
            host_tier_bytes=args.host_tier_mb << 20,
            faults=FaultPlan(args.faults) if args.faults else None)

    pipeline = True if args.pipeline else None  # None -> env default
    if args.replicas > 1:
        from paddle_tpu.serving import Router, build_replicas
        sched = Router(build_replicas(make_engine, args.replicas,
                                      max_queue=args.max_queue,
                                      pipeline=pipeline))
        mode = f"router x{args.replicas} replicas"
    else:
        sched = RequestScheduler(make_engine(), max_queue=args.max_queue,
                                 pipeline=pipeline)
        mode = "single engine"
    if pipeline:
        mode += " [pipelined pump]"
    srv = ServingServer(sched, host=args.host, port=args.port).start()
    print(f"serving on {srv.url} [{mode}]  "
          f"(POST /v1/completions, GET /healthz, GET /readyz, "
          f"GET /metrics)")

    if args.forever:
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            print("draining ...")
            srv.stop(drain=True, timeout=30)
        return

    cl = ServingClient(host=srv.host, port=srv.port)
    print("healthz:", cl.healthz())

    rng = np.random.RandomState(0)
    prompt = list(map(int, rng.randint(1, cfg.vocab_size, 12)))
    out = cl.complete(prompt, max_tokens=24)
    print(f"blocking completion: {out['n']} tokens, state={out['state']}")

    print("streaming:", end=" ", flush=True)
    for ev in cl.stream_complete(prompt, max_tokens=24, temperature=0.8,
                                 seed=7):
        if ev.get("done"):
            print(f" [done n={ev['n']}]")
        else:
            print(*ev["tokens"], end=" ", flush=True)

    # a burst past max_seqs exercises the queue (and, if you shrink
    # --max-queue, 429 backpressure)
    burst = [list(map(int, rng.randint(1, cfg.vocab_size, 8)))
             for _ in range(2 * args.max_seqs)]
    threads = [threading.Thread(target=cl.complete, args=(p,),
                                kwargs={"max_tokens": 16})
               for p in burst]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    snap = cl.metrics()
    if args.replicas > 1:
        # router mode: per-replica snapshots ride under "replicas";
        # the router's own ledger is flat
        done = sum(int(s["pt_serving_requests_completed"]["value"])
                   for s in snap["replicas"].values())
        print(f"metrics: {done} completed over "
              f"{len(snap['replicas'])} replicas, "
              f"{int(snap['pt_router_dispatches']['value'])} dispatches"
              f" ({int(snap['pt_router_affinity_hits']['value'])}"
              f" affinity, {int(snap['pt_router_spills']['value'])}"
              f" spills, {int(snap['pt_router_failovers']['value'])}"
              f" failovers)")
        for rid, s in snap["replicas"].items():
            print(f"  {rid}: {int(s['pt_serving_requests_completed']['value'])}"
                  f" completed, prefix hit rate"
                  f" {s['pt_prefix_hit_rate']['value']:.2f}")
    else:
        ttft = snap["pt_serving_ttft_seconds"]
        print(f"metrics: "
              f"{int(snap['pt_serving_requests_completed']['value'])}"
              f" completed, ttft p50 {ttft['p50'] * 1e3:.1f} ms"
              f" p99 {ttft['p99'] * 1e3:.1f} ms, queue peak"
              f" {int(snap['pt_serving_queue_depth_peak']['value'])},"
              f" device steps"
              f" {int(snap['pt_serving_device_steps']['value'])}")
    print("graceful stop:", srv.stop(drain=True, timeout=30))


if __name__ == "__main__":
    main()
