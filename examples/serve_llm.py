"""Online LLM serving: continuous batching, int8 KV cache, speculative
decoding, chunked prefill.

    python examples/serve_llm.py                     # greedy, fp cache
    python examples/serve_llm.py --spec 4            # prompt-lookup spec
    python examples/serve_llm.py --cache int8
    python examples/serve_llm.py --spec 4 --chunked  # split-fuse prefill

Shows: ServingEngine admission/eviction over the paged KV pool,
per-request sampling params, and the r4 serving features — all
token-exact vs plain greedy decode (docs/serving.md).
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# default to CPU unless explicitly aimed at the chip: the axon TPU tunnel
# comes and goes, and a wedged plugin otherwise kills backend auto-select
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import argparse
import time

import numpy as np

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", type=int, default=0,
                    help="speculative chunk width G (0 = plain decode)")
    ap.add_argument("--chunked", action="store_true",
                    help="chunked prefill (needs --spec >= 2)")
    ap.add_argument("--cache", choices=["fp", "int8"], default="fp")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--logprobs", action="store_true",
                    help="record per-token raw-model logprobs")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree (0 = single device); "
                         "shards weights + KV pool over a tp mesh")
    args = ap.parse_args()

    cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=8,
                           kv_heads=4, ffn=256, seq=256)
    params = M.init_params(cfg, seed=0)
    mesh = None
    if args.tp > 1:
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:args.tp]).reshape(args.tp),
                    ("tp",))
    eng = ServingEngine(
        params, cfg, max_seqs=4, max_seq_len=256, page_size=16,
        cache_dtype="int8" if args.cache == "int8" else None,
        spec_decode=args.spec, chunked_prefill=args.chunked, mesh=mesh)

    rng = np.random.RandomState(0)
    for i in range(args.requests):
        prompt = list(rng.randint(1, cfg.vocab_size,
                                  int(rng.randint(8, 48))))
        # mix greedy and sampled requests in one batch
        kw = {} if i % 3 else {"temperature": 0.8, "top_k": 16, "seed": i}
        eng.submit(Request(f"req{i}", prompt,
                           max_new_tokens=args.new_tokens,
                           logprobs=args.logprobs, **kw))

    t0 = time.perf_counter()
    done = eng.run()
    dt = time.perf_counter() - t0
    total = sum(len(r.output) for r in done)
    print(f"{len(done)} requests, {total} tokens in {dt:.2f}s "
          f"({total / dt:.1f} tok/s), {eng.device_steps} device steps")
    if args.spec > 1:
        rate = eng.spec_accepted / max(eng.spec_drafted, 1)
        print(f"speculative: {eng.spec_drafted} drafted, "
              f"{eng.spec_accepted} accepted ({rate:.0%})")
    for r in done[:3]:
        print(f"  {r.rid}: {r.output[:10]}{'...' if len(r.output) > 10 else ''}")
        if r.logprobs is not None:
            print(f"    logprobs: {[round(x, 3) for x in r.logprobs[:6]]}...")


if __name__ == "__main__":
    main()
