"""DLRM CTR training over parameter-server sparse tables.

    python examples/train_dlrm_ps.py                 # in-process shards
    python examples/train_dlrm_ps.py --sockets       # real TCP PS tier
    python examples/train_dlrm_ps.py --cpp           # native C++ shards

Shows: host-RAM SparseTable shards (per-row adagrad), the
DistributedEmbedding pull/push flow around a jitted dense tower, the
same run over the socket tier the multi-process deployment uses, and
the libptps native backend (docs/distributed.md § Parameter-server
mode).
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# default to CPU unless explicitly aimed at the chip: the axon TPU tunnel
# comes and goes, and a wedged plugin otherwise kills backend auto-select
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import argparse
import time

import numpy as np

from paddle_tpu.distributed import ps
from paddle_tpu.models.dlrm import DLRMConfig, DLRMTrainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sockets", action="store_true",
                    help="run the shards behind the real TCP PS tier")
    ap.add_argument("--cpp", action="store_true",
                    help="native C++ shards (csrc/ptps.cpp) instead of "
                         "the Python tier (implies --sockets)")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--shards", type=int, default=2)
    args = ap.parse_args()

    cfg = DLRMConfig(emb_dim=16, n_sparse=8, dense_dim=13,
                     bottom=(64, 32), top=(64, 32))
    spec = dict(optimizer="adagrad", lr=0.05)   # one spec, all backends

    def mk_table(s):
        return ps.SparseTable(cfg.emb_dim, seed=s, **spec)

    servers = []
    if args.cpp:
        for s in range(args.shards):
            servers.append(ps.CppPSServer(cfg.emb_dim, seed=s, **spec))
    elif args.sockets:
        for s in range(args.shards):
            srv = ps.EmbeddingPSServer([mk_table(s)])
            srv.serve_in_thread()
            servers.append(srv)
    if servers:
        _os.environ["PT_PS_ENDPOINTS"] = ",".join(s.endpoint
                                                  for s in servers)
        client = ps.init_worker()
        print(f"PS tier: {len(servers)} "
              f"{'native C++' if args.cpp else 'python'} socket servers "
              f"({_os.environ['PT_PS_ENDPOINTS']})")
    else:
        client = ps.PSClient([mk_table(s) for s in range(args.shards)])

    tr = DLRMTrainer(cfg, client, seed=0, lr=0.05)
    rng = np.random.RandomState(0)

    def batch():
        ids = rng.randint(0, 100_000, (args.batch, cfg.n_sparse))
        ids = ids.astype(np.int64) \
            + np.arange(cfg.n_sparse, dtype=np.int64)[None] * 1_000_003
        dense = rng.randn(args.batch, cfg.dense_dim).astype(np.float32)
        y = ((dense[:, 0] + (ids[:, 0] % 2) * 1.5 - 0.7) > 0)
        return ids, dense, y.astype(np.float32)

    t0 = time.perf_counter()
    for it in range(args.steps):
        loss = tr.train_step(*batch())
        if it % 10 == 0 or it == args.steps - 1:
            print(f"step {it:3d}  loss {loss:.4f}  "
                  f"rows materialized {len(client)}")
    dt = time.perf_counter() - t0
    print(f"{args.steps * args.batch / dt:.0f} examples/s "
          f"(PS round-trip included)")

    if servers:
        ps.stop_worker()
        for s in servers:
            s.close()


if __name__ == "__main__":
    main()
