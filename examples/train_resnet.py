"""ResNet training two ways: paddle-style eager and compiled Trainer.

    python examples/train_resnet.py --arch resnet18 --mode trainer
"""
from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

_os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# default to CPU unless explicitly aimed at the chip: the axon TPU tunnel
# comes and goes, and a wedged plugin otherwise kills backend auto-select
if _os.environ.get("PT_EXAMPLE_TPU") != "1":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import argparse
import time

import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.parallel import create_mesh, Trainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--mode", choices=["eager", "trainer"], default="trainer")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--img", type=int, default=64)
    args = ap.parse_args()

    net = getattr(pt.vision.models, args.arch)(num_classes=10)
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=net.parameters(),
                                weight_decay=1e-4)
    rng = np.random.RandomState(0)

    def batch():
        x = rng.randn(args.batch, 3, args.img, args.img).astype(np.float32)
        y = rng.randint(0, 10, args.batch)
        return x, y

    if args.mode == "eager":
        lossf = pt.nn.CrossEntropyLoss()
        for step in range(args.steps):
            x, y = batch()
            loss = lossf(net(pt.to_tensor(x)), pt.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            print(f"step {step} loss {float(loss):.4f}")
        return

    mesh = create_mesh({"dp": -1})

    def loss_fn(model, data):
        x, y = data
        return pt.nn.functional.cross_entropy(model(x), y)

    tr = Trainer(net, opt, loss_fn, mesh=mesh,
                 batch_spec=(P("dp"), P("dp")))
    t0 = time.perf_counter()
    for step in range(args.steps):
        loss = tr.step(batch())
        print(f"step {step} loss {float(loss):.4f}")
    print(f"{args.steps / (time.perf_counter() - t0):.2f} steps/s")


if __name__ == "__main__":
    main()
