"""paddle_tpu: a TPU-native deep-learning framework with the PaddlePaddle
API surface, built from scratch on JAX/XLA/Pallas.

Functional core (pure jnp/lax ops, jit/pjit/shard_map for execution) with
an imperative paddle-shaped shell (Tensor + tape autograd + nn.Layer).
"""
from __future__ import annotations

import os as _os

import jax as _jax

# int64/float64 are part of the paddle dtype contract; f64 is CPU/test-only
# (TPU emulates it) — models use fp32/bf16 explicitly.
_jax.config.update("jax_enable_x64", True)

from ._core import dtypes as _dtypes
from ._core.dtypes import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo,
)
from ._core.tensor import Tensor, Parameter  # noqa: F401
from ._core.state import seed, get_rng_state  # noqa: F401
from ._core import state as _state

from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation
from .tensor.logic import is_tensor  # noqa: F401
from .tensor.attribute import rank, is_complex, is_floating_point, is_integer  # noqa: F401

from .autograd import no_grad, enable_grad, grad  # noqa: F401
from .framework import dtype, in_dynamic_mode, set_grad_enabled  # noqa: F401

from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import device  # noqa: F401
from . import linalg  # noqa: F401
from . import fft  # noqa: F401
from . import sparse  # noqa: F401
from . import incubate  # noqa: F401
from . import static  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
from . import regularizer  # noqa: F401
from . import version  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import onnx  # noqa: F401
from . import inference  # noqa: F401
from . import signal  # noqa: F401
from . import geometric  # noqa: F401
from . import utils  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary  # noqa: F401
from .hapi.flops import flops  # noqa: F401
from .device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_xpu,
    is_compiled_with_rocm, is_compiled_with_custom_device, CPUPlace, TPUPlace,
    CUDAPlace, synchronize,
)
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .jit.api import disable_static, enable_static  # noqa: F401

# random-key context for compiled training steps (tpu-native extension)
random_key_context = _state.prng.key_ctx

__version__ = "0.1.0"


def batch(reader, batch_size, drop_last=False):
    """paddle.batch parity (python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
