"""paddle_tpu: a TPU-native deep-learning framework with the PaddlePaddle
API surface, built from scratch on JAX/XLA/Pallas.

Functional core (pure jnp/lax ops, jit/pjit/shard_map for execution) with
an imperative paddle-shaped shell (Tensor + tape autograd + nn.Layer).
"""
from __future__ import annotations

import os as _os

import jax as _jax

# int64/float64 are part of the paddle dtype contract; f64 is CPU/test-only
# (TPU emulates it) — models use fp32/bf16 explicitly.
_jax.config.update("jax_enable_x64", True)

from ._core import dtypes as _dtypes
from ._core.dtypes import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo,
)
from ._core.tensor import Tensor, Parameter  # noqa: F401
from ._core.state import seed, get_rng_state  # noqa: F401
from ._core import state as _state

from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation
from .tensor.logic import is_tensor  # noqa: F401
from .tensor.attribute import rank, is_complex, is_floating_point, is_integer  # noqa: F401

from .autograd import no_grad, enable_grad, grad  # noqa: F401
from .framework import dtype, in_dynamic_mode, set_grad_enabled  # noqa: F401

from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import device  # noqa: F401
# NB: `from . import linalg` would NOT import our linalg.py here — the
# `from .tensor import *` above already bound the name to the
# tensor.linalg submodule, and _handle_fromlist skips importing when the
# attribute exists. Import the real module explicitly and rebind.
import paddle_tpu.linalg as _linalg_full  # noqa: E402

linalg = _linalg_full
from . import fft  # noqa: F401
from . import sparse  # noqa: F401
from . import incubate  # noqa: F401
from . import static  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
from . import regularizer  # noqa: F401
from . import version  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import onnx  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import observability  # noqa: F401
from . import signal  # noqa: F401
from . import geometric  # noqa: F401
from . import utils  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import sysconfig  # noqa: F401
from .batch import batch  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary  # noqa: F401
from .hapi.flops import flops  # noqa: F401
from .device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_xpu,
    is_compiled_with_rocm, is_compiled_with_custom_device, CPUPlace, TPUPlace,
    CUDAPlace, synchronize,
)
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .jit.api import disable_static, enable_static  # noqa: F401

# random-key context for compiled training steps (tpu-native extension)
random_key_context = _state.prng.key_ctx

__version__ = "0.1.0"


# ---------------------------------------------------------------------------
# top-level namespace completion (reference python/paddle/__init__.py __all__):
# constants, remaining ops, and generated inplace `op_` variants
# ---------------------------------------------------------------------------
import math as _math

inf = float("inf")
nan = float("nan")
pi = _math.pi
e = _math.e
newaxis = None

from .tensor.extras import (  # noqa: F401
    sinc, baddbmm, cartesian_prod, pdist, histogram_bin_edges, combinations,
    reduce_as, diagonal_scatter, cast, less, negative,
    positive, reverse, tolist, is_grad_enabled, set_printoptions,
    from_dlpack, to_dlpack, check_shape, disable_signal_handler,
    log_normal_, bernoulli_, where_,
)
from .tensor.attribute import shape  # noqa: F401
from .nn.layer.layers import ParamAttr  # noqa: F401
from .device import CUDAPinnedPlace  # noqa: F401
from .framework import set_flags, get_flags  # noqa: F401
from .distributed import DataParallel  # noqa: F401


class LazyGuard:
    """reference: paddle.LazyGuard — lazy parameter init. Params here are
    created eagerly but cheaply (jax arrays on first use), so the guard
    is a transparent context."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


# paddle's string dtypes (pstring/raw) exist for Tensor metadata only
pstring = "pstring"
raw = "raw"

from .tensor.logic import bitwise_not as bitwise_invert  # noqa: F401

# generated inplace variants: every paddle `op_` whose base op exists
from .tensor import extras as _extras


def _gen_inplace():
    g = globals()
    names = [
        "abs", "acos", "addmm", "asin", "atan", "atanh", "baddbmm",
        "bernoulli", "bitwise_and", "bitwise_invert", "bitwise_not",
        "bitwise_or", "bitwise_xor", "bitwise_left_shift",
        "bitwise_right_shift", "cast", "ceil", "clip", "copysign", "cos",
        "cosh", "cumprod", "cumsum", "digamma", "divide", "equal", "erf",
        "erfinv", "exp", "expm1", "fill", "flatten", "floor",
        "floor_divide", "floor_mod", "frac", "gammainc", "gammaincc",
        "gammaln", "gcd", "greater_equal", "greater_than", "hypot", "i0",
        "index_add", "index_fill", "index_put",
        "lcm", "ldexp", "lerp", "less", "less_equal", "less_than",
        "lgamma", "log", "log10", "log1p", "log2", "logical_and",
        "logical_not", "logical_or", "logical_xor", "logit",
        "masked_fill", "masked_scatter", "mod", "multigammaln",
        "multiply", "nan_to_num", "neg", "polygamma", "pow", "reciprocal",
        "remainder", "renorm", "round", "rsqrt", "scale", "sigmoid",
        "sign", "sin", "sinc", "sinh", "sqrt", "square", "squeeze",
        "subtract", "t", "tan", "tanh", "tril", "triu", "trunc",
        "unsqueeze", "transpose",
    ]
    for base in names:
        fn = g.get(base)
        iname = base + "_"
        if callable(fn) and iname not in g:
            g[iname] = _extras.make_inplace(fn, iname)
    # add_/sub_ style aliases paddle also exports
    for base, iname in (("add", "add_"), ("subtract", "sub_"),
                        ("multiply", "mul_"), ("divide", "div_")):
        fn = g.get(base)
        if callable(fn) and iname not in g:
            g[iname] = _extras.make_inplace(fn, iname)


_gen_inplace()
del _gen_inplace
