"""Version compatibility shims for the jax API surface.

`shard_map` graduated out of jax.experimental in 0.8 with two renames:
`check_rep` -> `check_vma`, and the manual-axes selection flipped from
`auto={axes left automatic}` to `axis_names={axes made manual}`. The
tree is written against the new spelling; this shim lets it run on the
0.4.x experimental API as well.
"""
from __future__ import annotations

import jax
from jax import lax


def axis_size(axis_name):
    """lax.axis_size appeared after 0.4.x; the old spelling is the
    constant-folded psum of 1 over the axis (static under trace)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(fn, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    # axis_names (new API) leaves the other mesh axes automatic; the
    # 0.4.x `auto=` equivalent is unimplemented for eager calls and
    # miscompiles some gradient graphs, so run all-manual instead.
    # Equivalent for every in-tree call site: their in/out_specs
    # replicate the non-manual axes and the bodies are rank-local
    # (only collectives over the named axis), so each auto-axis rank
    # computes the same replica either way.
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
