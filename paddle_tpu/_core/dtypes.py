"""Dtype system for paddle_tpu.

Mirrors the reference dtype surface (paddle/phi/common/data_type.h,
python/paddle/framework/dtype.py) on top of numpy/jax dtypes. TPU-native
notes: bfloat16 is the first-class reduced precision type (MXU-native);
float64 exists for CPU-side numerics/tests but is emulated on TPU.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects are numpy dtypes (jax-compatible).
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_STR2DTYPE = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "bfloat16": bfloat16,
    "float32": float32, "float64": float64, "complex64": complex64,
    "complex128": complex128, "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # paddle legacy VarDesc-style names
    "BOOL": bool_, "UINT8": uint8, "INT8": int8, "INT16": int16,
    "INT32": int32, "INT64": int64, "FP16": float16, "BF16": bfloat16,
    "FP32": float32, "FP64": float64,
}

FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
INTEGER = {uint8, int8, int16, int32, int64}
COMPLEX = {complex64, complex128}

_default_dtype = float32


def convert_dtype(dtype):
    """Normalize any dtype-ish (str, np.dtype, jnp dtype, Tensor dtype) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.split(".")[-1]
        if key in _STR2DTYPE:
            return _STR2DTYPE[key]
        return np.dtype(key)
    if isinstance(dtype, np.dtype):
        return dtype
    return np.dtype(dtype)


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"set_default_dtype only supports float dtypes, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def is_floating_point_dtype(d):
    return convert_dtype(d) in FLOATING


def is_integer_dtype(d):
    return convert_dtype(d) in INTEGER


def is_complex_dtype(d):
    return convert_dtype(d) in COMPLEX


def finfo(dtype):
    return ml_dtypes.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(convert_dtype(dtype))


def promote_types(a, b):
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))


def dtype_name(d):
    d = convert_dtype(d)
    if d == bfloat16:
        return "bfloat16"
    return d.name
