"""Tape backward engine.

Replaces the reference's C++ autograd engine
(paddle/fluid/imperative/basic_engine.cc): topological walk over recorded
TapeNodes, per-node VJP from jax.vjp, cotangent accumulation into leaf
.grad. Gradient math itself is JAX's — there is no hand-written grad-op
registry to maintain.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor, TapeNode, _float0_like


def _topo_order(root_nodes):
    """Return nodes in reverse-topological (output→input) order."""
    visited = set()
    order = []

    for root in root_nodes:
        if root is None or id(root) in visited:
            continue
        stack = [(root, False)]
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in visited:
                continue
            visited.add(id(node))
            stack.append((node, True))
            for pnode, _, _ in node.input_links:
                if pnode is not None and id(pnode) not in visited:
                    stack.append((pnode, False))
    order.reverse()
    return order


def _run_backward(outputs, out_grads, inputs=None, accumulate_into_leaves=True,
                  retain_graph=False):
    """Core reverse pass.

    outputs: list[Tensor]; out_grads: list[array] seed cotangents.
    inputs: optional list[Tensor] — if given, return their grads (paddle.grad
    semantics); leaves still get .grad accumulated iff accumulate_into_leaves.
    """
    cotangents: dict[int, list] = {}
    nodes: dict[int, TapeNode] = {}
    # direct input grads (for tensors requested in `inputs` that are also outputs
    # or leaves)
    direct: dict[int, object] = {}
    input_ids = {id(t) for t in inputs} if inputs else set()

    def seed(t: Tensor, g):
        if t._node is None:
            _accum_tensor(t, g)
            return
        key = id(t._node)
        nodes[key] = t._node
        lst = cotangents.setdefault(key, [None] * len(t._node.raw_outputs))
        lst[t._out_idx] = g if lst[t._out_idx] is None else lst[t._out_idx] + g

    hooked_leaves: dict[int, Tensor] = {}
    pass_contrib: dict[int, object] = {}  # THIS pass's grad per hooked leaf

    def _accum_tensor(t: Tensor, g):
        if _float0_like(g):
            return
        if g.shape != tuple(t._value.shape):
            g = jnp.reshape(jnp.broadcast_to(g, t._value.shape), t._value.shape) \
                if g.size == t.size else g
        if getattr(t, "_leaf_hooks", None):
            # hooks fire once per backward PASS with this pass's grad
            # (not the cross-pass .grad accumulation), for backward()
            # and grad() alike — contributions from multiple consumers
            # sum here and the hook fires after the walk
            k = id(t)
            hooked_leaves[k] = t
            pass_contrib[k] = g if k not in pass_contrib \
                else pass_contrib[k] + g
        if id(t) in input_ids:
            direct[id(t)] = g if id(t) not in direct else direct[id(t)] + g
        if accumulate_into_leaves and (t.is_leaf or t._retain_grads):
            if t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._value + g, stop_gradient=True)

    for t, g in zip(outputs, out_grads):
        if t.stop_gradient:
            continue
        seed(t, g)

    order = _topo_order([t._node for t in outputs if t._node is not None])

    for node in order:
        key = id(node)
        cts = cotangents.get(key)
        if cts is None or all(c is None for c in cts):
            continue
        hooks = getattr(node, "_out_hooks", None)
        if hooks:
            # topo order guarantees every consumer has contributed, so
            # cts[j] is the FULL gradient of output j here — the
            # register_hook contract (fire once; a returned tensor
            # replaces the grad seen upstream)
            for j, slot in hooks.items():
                if j < len(cts) and cts[j] is not None:
                    for fn in list(slot.values()):
                        r = fn(Tensor(cts[j], stop_gradient=True))
                        if r is not None:
                            cts[j] = r._value if isinstance(r, Tensor) \
                                else jnp.asarray(r)
        in_grads = node.vjp(cts)
        for t, (pnode, pidx, sg), g in zip(node.input_tensors,
                                           node.input_links, in_grads):
            if t is None or sg or _float0_like(g):
                continue
            # route via the producer link + stop_gradient frozen at record
            # time, NOT t._node / t.stop_gradient (an in-place op may have
            # redirected or severed them since)
            if pnode is not None:
                nkey = id(pnode)
                nodes[nkey] = pnode
                lst = cotangents.setdefault(nkey, [None] * len(pnode.raw_outputs))
                lst[pidx] = g if lst[pidx] is None else lst[pidx] + g
                if t._retain_grads or id(t) in input_ids:
                    _accum_tensor(t, g)
            else:
                _accum_tensor(t, g)
        if not retain_graph:
            cotangents[key] = None

    for k, t in hooked_leaves.items():
        g0 = pass_contrib[k]
        g_new = g0
        for fn in list(t._leaf_hooks.values()):
            r = fn(Tensor(g_new, stop_gradient=True))
            if r is not None:
                g_new = r._value if isinstance(r, Tensor) else jnp.asarray(r)
        if g_new is g0:
            continue
        # a replacement swaps only THIS pass's contribution — prior
        # .grad accumulation and other inputs' grads stay intact
        if accumulate_into_leaves and (t.is_leaf or t._retain_grads) \
                and t.grad is not None:
            t.grad = Tensor(t.grad._value - g0 + g_new, stop_gradient=True)
        if k in direct:
            direct[k] = direct[k] - g0 + g_new

    return direct


def backward(tensor: Tensor, grad_tensor=None, retain_graph=False):
    if tensor.stop_gradient:
        raise RuntimeError(
            "Tensor has stop_gradient=True; nothing to backpropagate.")
    if grad_tensor is None:
        g = jnp.ones_like(tensor._value)
    else:
        g = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    _run_backward([tensor], [g], retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad parity (python/paddle/autograd/autograd.py)."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        gouts = [jnp.ones_like(o._value) for o in outs]
    else:
        gl = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
        gouts = [jnp.ones_like(o._value) if g is None else
                 (g._value if isinstance(g, Tensor) else jnp.asarray(g))
                 for o, g in zip(outs, gl)]
    direct = _run_backward(outs, gouts, inputs=ins, accumulate_into_leaves=False,
                           retain_graph=True)
    result = []
    for t in ins:
        g = direct.get(id(t))
        if g is None:
            if not allow_unused:
                result.append(Tensor(jnp.zeros_like(t._value), stop_gradient=True))
            else:
                result.append(None)
        else:
            result.append(Tensor(g, stop_gradient=not create_graph))
    return result
