"""Global interpreter state: grad mode, PRNG threading, AMP state.

Reference parity: paddle/fluid/imperative/tracer.cc (has_grad / amp state)
and python/paddle/framework/random.py — redesigned around JAX's explicit
PRNG keys so that randomness is reproducible and trace-safe on TPU.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class _ThreadState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.amp_dtype = None      # active autocast dtype (np dtype) or None
        self.amp_level = "O0"
        self.amp_custom_white = set()
        self.amp_custom_black = set()


_state = _ThreadState()


def grad_enabled() -> bool:
    return _state.grad_enabled


@contextlib.contextmanager
def no_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


def amp_state():
    return _state


# ---------------------------------------------------------------------------
# PRNG: a stateful global key for eager mode, plus an explicit key-context
# stack so compiled (traced) code can thread step-dependent keys through
# random ops (dropout etc.) without retracing.
# ---------------------------------------------------------------------------
class _PRNGState:
    def __init__(self, seed: int = 0):
        self._np_lock = threading.Lock()
        self.seed(seed)
        self._ctx_stack = []  # list of [key, counter]

    def seed(self, s: int):
        self._seed = int(s)
        # LAZY: creating the key here would initialize the jax backend at
        # `import paddle_tpu` time — seconds of TPU-plugin setup (or a
        # deadlock when another process holds the TPU tunnel) before any
        # user code runs. The key materializes on first random use.
        self._key = None
        self._eager_counter = 0

    def _base_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def next_key(self):
        """Return a fresh PRNG key.

        Inside a key context (compiled path) keys derive from the pushed
        (possibly traced) key via fold_in with a static counter; in eager
        mode we advance the global stateful key.
        """
        if self._ctx_stack:
            entry = self._ctx_stack[-1]
            k = jax.random.fold_in(entry[0], entry[1])
            entry[1] += 1
            return k
        self._eager_counter += 1
        return jax.random.fold_in(self._base_key(), self._eager_counter)

    def next_np_seed(self) -> int:
        """Derive a 32-bit seed for host-side numpy Generators (samplers,
        dataset shuffles). Deterministic under seed(); each caller gets its
        own Generator so no thread shares mutable numpy RNG state."""
        with self._np_lock:
            self._eager_counter += 1
            return (self._seed * 1000003 + self._eager_counter) & 0xFFFFFFFF

    @contextlib.contextmanager
    def key_ctx(self, key):
        self._ctx_stack.append([key, 0])
        try:
            yield
        finally:
            self._ctx_stack.pop()


prng = _PRNGState(0)


def seed(s: int):
    prng.seed(s)
    return prng


def get_rng_state():
    return {"seed": prng._seed, "counter": prng._eager_counter}


def set_rng_state(st):
    prng.seed(st["seed"])
    prng._eager_counter = st["counter"]
