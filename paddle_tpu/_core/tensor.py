"""Tensor: imperative shell over jax.Array, with tape autograd.

Architecture (tpu-first, NOT a port):
  * Every op has a *pure functional core* (jnp/lax) — that is what runs
    under jit/pjit and what XLA fuses onto the MXU.
  * Eager mode wraps results in `Tensor` and records a lightweight tape
    node `(fn, raw_inputs, kwargs)`. `backward()` walks the tape and gets
    each node's VJP from `jax.vjp` on the pure core — so the "gradient op
    registry" of the reference (paddle/fluid/imperative/ + ops_autogen)
    is replaced wholesale by JAX's AD.
  * Under `jax.jit` tracing the tape is bypassed (inputs are tracers);
    compiled training uses `jax.value_and_grad` over functional_call.

Reference parity: python/paddle/tensor/tensor.py (method surface),
paddle/fluid/imperative/tracer.cc + basic_engine.cc (tape + engine).
"""
from __future__ import annotations

import functools
import numpy as np
import jax
import jax.numpy as jnp

from . import dtypes as _dt
from .state import grad_enabled

Tracer = jax.core.Tracer


def _is_tracer(x):
    return isinstance(x, Tracer)


class Place:
    def __init__(self, kind: str, idx: int = 0):
        self._kind, self._idx = kind, idx

    def __repr__(self):
        return f"Place({self._kind}:{self._idx})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self._kind, self._idx) == (other._kind, other._idx)

    def is_tpu_place(self):
        return self._kind == "tpu"

    def is_cpu_place(self):
        return self._kind == "cpu"

    def is_gpu_place(self):  # parity shim: no CUDA in this framework
        return False


# active (pack, unpack) hook pairs — see autograd.saved_tensors_hooks
_saved_tensor_hooks: list = []

# optional per-op observer (amp.debugging stats/tensor-checker); None in
# the hot path so the common case costs one None check per eager op
_op_observer = None


class TapeNode:
    """One recorded op. VJP is derived lazily via jax.vjp on the pure fn."""

    __slots__ = ("fn", "kwargs", "raw_inputs", "input_tensors", "raw_outputs",
                 "multi", "name", "input_links", "_unpack", "_out_hooks")

    def __init__(self, fn, kwargs, raw_inputs, input_tensors, raw_outputs, multi, name):
        self.fn = fn
        self.kwargs = kwargs
        if _saved_tensor_hooks:
            # pack only the slots that are saved TENSORS (reference
            # semantics) — axis ints, shapes, and raw index arrays pass
            # through untouched so replay/vjp see them as recorded
            pack, unpack = _saved_tensor_hooks[-1]
            packed_slots = tuple(isinstance(t, Tensor)
                                 for t in input_tensors)
            self.raw_inputs = tuple(
                pack(r) if is_t else r
                for r, is_t in zip(raw_inputs, packed_slots))
            self._unpack = (unpack, packed_slots)
        else:
            self.raw_inputs = raw_inputs
            self._unpack = None
        self.input_tensors = input_tensors
        self.raw_outputs = raw_outputs
        self.multi = multi
        self.name = name
        # Producer links frozen at record time. The tape is snapshot-
        # consistent: raw_inputs already captures input *values* as of the
        # record, so routing must capture input *history* then too — if it
        # resolved t._node (or t.stop_gradient) at backward time instead,
        # an in-place mutation of t between record and backward would
        # re-route or sever this node's cotangent (wrong/missing grads for
        # every earlier consumer of t). Entries: (producer, out_idx,
        # stop_gradient) as of the record.
        self.input_links = tuple(
            (t._node, t._out_idx, t.stop_gradient) if isinstance(t, Tensor)
            else (None, 0, True)
            for t in input_tensors)

    def vjp(self, cotangents):
        """cotangents: list aligned with raw_outputs (None → zeros)."""
        fn, kw = self.fn, self.kwargs
        closed = (lambda *a: fn(*a, **kw)) if kw else fn
        if self._unpack is None:
            raw = self.raw_inputs
        else:
            unpack, packed_slots = self._unpack
            raw = tuple(unpack(r) if is_t else r
                        for r, is_t in zip(self.raw_inputs, packed_slots))
        _, vjp_fn = jax.vjp(closed, *raw)
        if self.multi:
            ct = tuple(
                jnp.zeros_like(o) if c is None else c
                for o, c in zip(self.raw_outputs, cotangents)
            )
        else:
            ct = cotangents[0]
            if ct is None:
                ct = jnp.zeros_like(self.raw_outputs[0])
        return vjp_fn(ct)


def _float0_like(g):
    return g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0)


_HOOK_COUNTER = [0]


def _next_hook_id():
    _HOOK_COUNTER[0] += 1
    return _HOOK_COUNTER[0]


class _HookRemoveHelper:
    """Returned by Tensor.register_hook — reference parity with
    TensorHookRemoveHelper (remove() deregisters)."""

    def __init__(self, slot, hid):
        self._slot, self._hid = slot, hid

    def remove(self):
        return self._slot.pop(self._hid, None) is not None


class Tensor:
    """paddle_tpu Tensor: value + autograd metadata.

    `_value` is a jax.Array (or a tracer during jit tracing). `_node` /
    `_out_idx` link to the producing TapeNode for backward.
    """

    __slots__ = ("_value", "stop_gradient", "grad", "_node", "_out_idx",
                 "name", "_retain_grads", "persistable", "dist_spec",
                 "_leaf_hooks", "__weakref__")

    def __init__(self, value, stop_gradient=True, name=None):
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name
        self._retain_grads = False
        self.persistable = False
        self.dist_spec = None  # PartitionSpec over the global mesh (GSPMD)
        self._leaf_hooks = None  # register_hook on leaves (dict id → fn)

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        if _is_tracer(self._value):
            return Place("tpu", 0)
        try:
            dev = list(self._value.devices())[0]
            return Place(dev.platform, dev.id)
        except Exception:
            return Place("cpu", 0)

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import tensor as _t
        return _t.linalg.t(self)

    @property
    def mT(self):
        return _apply(lambda x: jnp.swapaxes(x, -1, -2), {}, self, name="mT")

    @property
    def real(self):
        return _apply(jnp.real, {}, self, name="real")

    @property
    def imag(self):
        return _apply(jnp.imag, {}, self, name="imag")

    def numel(self):
        return Tensor(jnp.asarray(self.size, dtype=jnp.int64), stop_gradient=True)

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    def element_size(self):
        return self.dtype.itemsize

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return np.asarray(self._value).item(*args)
        return np.asarray(self._value).item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype):
        d = _dt.convert_dtype(dtype)
        return _apply(lambda x: x.astype(d), {}, self, name="cast")

    cast = astype

    def cpu(self):
        return Tensor(jax.device_get(self._value), self.stop_gradient)

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                continue  # single logical device space under jit
            try:
                d = _dt.convert_dtype(a)
                out = out.astype(d)
            except Exception:
                pass
        return out

    def clone(self):
        return _apply(lambda x: x + jnp.zeros((), x.dtype), {}, self, name="clone")

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- misc parity (reference: base/dygraph/tensor_patch_methods.py) -------
    def value(self):
        """Reference parity: returns the underlying variable — here the
        Tensor itself (there is no separate VarBase)."""
        return self

    def apply(self, func):
        """Return func(self) (tensor_patch_methods.py:apply). Like the
        reference, refuses tensors that require grad — apply is a
        data-editing escape hatch, not a differentiable op."""
        if not self.stop_gradient:
            raise RuntimeError(
                "Cannot apply function on a tensor that requires grad; "
                "detach() first or use normal ops for a differentiable "
                "path.")
        return func(self)

    def apply_(self, func):
        """In-place apply: self <- func(self) (same grad guard)."""
        out = self.apply(func)
        v = out._value if isinstance(out, Tensor) else jnp.asarray(out)
        self._replace(v.astype(self.dtype) if v.dtype != self.dtype else v)
        return self

    def to_dense(self):
        """Dense tensors are their own dense form (SparseCooTensor
        overrides; parity: sparse_to_dense)."""
        return self

    def to_sparse_coo(self, sparse_dim):
        """Dense → COO with `sparse_dim` leading sparse axes
        (tensor_patch_methods.py:1212 → sparse_to_sparse_coo)."""
        from ..sparse import SparseCooTensor
        from jax.experimental import sparse as jsparse
        nd = self._value.ndim
        if not 0 < sparse_dim <= nd:
            raise ValueError(f"sparse_dim {sparse_dim} out of range for "
                             f"{nd}-d tensor")
        bcoo = jsparse.BCOO.fromdense(self._value, n_dense=nd - sparse_dim)
        return SparseCooTensor(bcoo, stop_gradient=self.stop_gradient)

    def __dlpack__(self, stream=None):
        return self._value.__dlpack__()

    def __dlpack_device__(self):
        return self._value.__dlpack_device__()

    # -- autograd -----------------------------------------------------------
    def retain_grads(self):
        self._retain_grads = True

    def gradient(self):
        """Grad as a numpy array (None when no grad) — legacy dygraph
        accessor (tensor_patch_methods.py:gradient)."""
        return None if self.grad is None else np.asarray(self.grad._value)

    def register_hook(self, hook):
        """Backward hook: called with this tensor's gradient during
        backward; returning a tensor replaces the gradient seen by
        upstream ops (tensor_patch_methods.py:502). Fires ONCE with the
        fully-accumulated gradient. Returns a remove() helper."""
        if self.stop_gradient:
            raise RuntimeError(
                "Cannot register hook on a tensor with stop_gradient=True")
        if self._node is not None:
            hooks = getattr(self._node, "_out_hooks", None)
            if hooks is None:
                hooks = self._node._out_hooks = {}
            slot = hooks.setdefault(self._out_idx, {})
        else:
            if self._leaf_hooks is None:
                self._leaf_hooks = {}
            slot = self._leaf_hooks
        hid = _next_hook_id()
        slot[hid] = hook
        return _HookRemoveHelper(slot, hid)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    @property
    def requires_grad(self):
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, v):
        self.stop_gradient = not v

    def backward(self, grad_tensor=None, retain_graph=False):
        from .engine import backward as _backward
        _backward(self, grad_tensor, retain_graph)

    # -- mutation (functional under the hood) --------------------------------
    def _replace(self, new_value, node=None, out_idx=0):
        self._value = new_value
        self._node = node
        self._out_idx = out_idx

    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        v = v.astype(self.dtype) if v.dtype != self.dtype else v
        self._replace(v)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, v):
        self._replace(jnp.full(self._value.shape, v, self.dtype))
        return self

    def zero_(self):
        return self.fill_(0)

    # -- indexing -----------------------------------------------------------
    def _convert_index(self, idx):
        def conv(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, (list, np.ndarray)):
                return jnp.asarray(i)
            return i
        if isinstance(idx, tuple):
            return tuple(conv(i) for i in idx)
        return conv(idx)

    def __getitem__(self, idx):
        idx = self._convert_index(idx)
        return _apply(lambda x: x[idx], {}, self, name="getitem")

    def __setitem__(self, idx, value):
        idx = self._convert_index(idx)
        if isinstance(value, Tensor):
            out = _apply(lambda x, v: x.at[idx].set(v.astype(x.dtype)), {}, self, value,
                         name="setitem")
        else:
            out = _apply(lambda x: x.at[idx].set(jnp.asarray(value).astype(x.dtype)), {},
                         self, name="setitem")
        self._replace(out._value, out._node, out._out_idx)
        self.stop_gradient = out.stop_gradient

    def __iter__(self):
        for i in range(self.shape[0] if self.ndim else 0):
            yield self[i]

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    # -- python scalar protocol ---------------------------------------------
    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __index__(self):
        return int(self._value)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        sg = self.stop_gradient
        if _is_tracer(self._value):
            return f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}, traced)"
        return (f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}, "
                f"place={self.place}, stop_gradient={sg},\n{np.asarray(self._value)})")

    __str__ = __repr__

    # Arithmetic operators are attached by paddle_tpu.tensor.math (monkey
    # patch pattern, mirroring python/paddle/tensor/__init__.py which stitches
    # methods onto the C++ Tensor).


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def wrap(value, stop_gradient=True, name=None):
    return Tensor(value, stop_gradient=stop_gradient, name=name)


def _apply(fn, kwargs, *args, name=None, multi=False, nondiff=()):
    """Run pure `fn` over (possibly Tensor) args; wrap outputs; record tape.

    nondiff: indices of args to close over statically (never differentiated,
    e.g. integer index arrays could stay positional — jax.vjp handles int
    args via float0, so this is only needed for non-array statics).
    """
    raw = tuple(unwrap(a) for a in args)
    tr = _get_trace()
    tracing = tr is not None and tr.enabled()
    t0 = _perf_counter() if tracing else None
    out = fn(*raw, **kwargs) if kwargs else fn(*raw)
    is_multi = multi or isinstance(out, (tuple, list))
    outs = tuple(out) if is_multi else (out,)
    if tracing and not any(_is_tracer(o) for o in outs if o is not None):
        # host dispatch-level span (async device work not awaited),
        # stamped with the current request's trace id when one is bound
        tr.record(name or fn.__name__, _perf_counter() - t0,
                  getattr(outs[0], "shape", None),
                  trace_id=_current_trace_id())

    if _op_observer is not None and not any(
            _is_tracer(o) for o in outs if o is not None):
        _op_observer(name or fn.__name__, outs)

    requires = grad_enabled() and any(
        isinstance(a, Tensor) and not a.stop_gradient for a in args
    )
    tensors_out = tuple(Tensor(o, stop_gradient=not requires) for o in outs)

    if requires and not any(_is_tracer(r) for r in raw if r is not None):
        in_tensors = tuple(a if isinstance(a, Tensor) else None for a in args)
        node = TapeNode(fn, kwargs, raw, in_tensors, outs, is_multi, name or fn.__name__)
        for i, t in enumerate(tensors_out):
            t._node = node
            t._out_idx = i
    if is_multi:
        return list(tensors_out) if isinstance(out, list) else tensors_out
    return tensors_out[0]


def apply(fn, *args, name=None, multi=False, **kwargs):
    """Public op-dispatch entry: paddle_tpu ops call this."""
    return _apply(fn, kwargs, *args, name=name, multi=multi)


_TRACE_MOD = None
from time import perf_counter as _perf_counter  # noqa: E402


def _get_trace():
    """Lazy utils.trace import: avoids a package-init cycle and costs
    one None-check per dispatch once resolved."""
    global _TRACE_MOD
    if _TRACE_MOD is None:
        try:
            from ..utils import trace as _t
            _TRACE_MOD = _t
        except ImportError:  # pragma: no cover - partial interpreter teardown
            return None
    return _TRACE_MOD


_TC_MOD = None


def _current_trace_id():
    """Lazy observability.trace_context import (same pattern as
    _get_trace); only reached when tracing is enabled."""
    global _TC_MOD
    if _TC_MOD is None:
        try:
            from ..observability import trace_context as _t
            _TC_MOD = _t
        except ImportError:  # pragma: no cover - partial teardown
            return None
    return _TC_MOD.current_trace_id()


# Register Tensor as a pytree so it can cross jit/pjit boundaries directly.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, ch: Tensor(ch[0], stop_gradient=aux[0], name=aux[1]),
)


class Parameter(Tensor):
    """Trainable leaf. stop_gradient defaults False (reference:
    python/paddle/base/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.persistable = True

    def __repr__(self):
        return "Parameter " + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._value,), (t.name, t.trainable)),
    lambda aux, ch: Parameter(ch[0], name=aux[0], trainable=aux[1]),
)
