"""Central registry of paddle_tpu environment knobs.

Every ``PT_*`` / ``PADDLE_TPU_*`` environment variable the tree reads
is declared HERE — name, default, one-line doc, type — and tpuracer's
TPL010 rule enforces it: an env read whose name is not declared below
is a lint error, and serving/observability code must read knobs
through the accessors in this module rather than `os.environ`
directly. `tools/gen_env_docs.py` renders the registry into
docs/env.md, so the operator-facing knob table can never drift from
the code.

This module is stdlib-only and importable standalone (tools load it
via importlib without triggering `paddle_tpu/__init__`), so CI boxes
without an accelerator stack can generate docs and lint against it.

Accessor semantics (chosen to match the historical call sites):

  * `env_str`    missing -> default, else the raw string.
  * `env_int` / `env_float`
                 missing OR empty/whitespace -> default.
  * `env_bool`   missing -> default; set -> False iff the stripped
                 value is "" or "0", True otherwise.

All accessors take `env=` (any mapping) so tests and fault drills can
inject an environment without mutating `os.environ`. Pattern knobs
(name containing ``*``, e.g. ``PT_SLO_*_TTFT_S``) declare a family:
concrete members resolve through the family's type and doc, with the
call site supplying the per-member default.
"""
from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass

__all__ = ["Knob", "declare", "knobs", "knob", "is_declared",
           "env_raw", "env_str", "env_int", "env_float", "env_bool"]

_UNSET = object()


@dataclass(frozen=True)
class Knob:
    """One declared environment knob. `default` is the value accessors
    return when the variable is unset (None = "auto/disabled" — the
    call site computes the effective value); `kind` is the accessor
    type ('str'|'int'|'float'|'bool'); `section` groups the docs
    table."""
    name: str
    default: object
    doc: str
    kind: str = "str"
    section: str = "general"

    @property
    def is_pattern(self):
        return "*" in self.name


_REGISTRY: dict = {}


def declare(name, default, doc, *, kind="str", section="general"):
    """Register one knob. Raises on duplicates, on names outside the
    PT_*/PADDLE_TPU_* namespaces, and on unknown kinds — the registry
    is the contract, so it validates loudly at import time."""
    if not (name.startswith("PT_") or name.startswith("PADDLE_TPU_")):
        raise ValueError(
            f"env knob {name!r}: must start with PT_ or PADDLE_TPU_")
    if name in _REGISTRY:
        raise ValueError(f"env knob {name!r} declared twice")
    if kind not in ("str", "int", "float", "bool"):
        raise ValueError(f"env knob {name!r}: unknown kind {kind!r}")
    if not doc or not str(doc).strip():
        raise ValueError(f"env knob {name!r}: doc line required")
    k = Knob(name=name, default=default, doc=" ".join(str(doc).split()),
             kind=kind, section=section)
    _REGISTRY[name] = k
    return k


def knobs():
    """All declared knobs, sorted by (section, name) — the docs-table
    order."""
    return sorted(_REGISTRY.values(), key=lambda k: (k.section, k.name))


def knob(name):
    """Exact or family (pattern) match; None when undeclared."""
    k = _REGISTRY.get(name)
    if k is not None:
        return k
    for pat, cand in _REGISTRY.items():
        if "*" in pat and fnmatch.fnmatchcase(name, pat):
            return cand
    return None


def is_declared(name):
    return knob(name) is not None


def _resolve(name, default):
    k = knob(name)
    if k is None:
        raise KeyError(
            f"env knob {name!r} is not declared in paddle_tpu/_env.py "
            "— add a declare(...) entry (TPL010 enforces this)")
    if default is _UNSET:
        if k.is_pattern:
            raise KeyError(
                f"env knob {name!r} matches family {k.name!r}: the "
                "call site must supply the per-member default")
        return k.default
    return default


def env_raw(name, env=None):
    """The raw string value, or None when unset. Still requires the
    name to be declared."""
    if knob(name) is None:
        _resolve(name, _UNSET)          # raises the undeclared error
    src = os.environ if env is None else env
    return src.get(name)


def env_str(name, default=_UNSET, env=None):
    default = _resolve(name, default)
    src = os.environ if env is None else env
    v = src.get(name)
    return default if v is None else v


def env_int(name, default=_UNSET, env=None):
    default = _resolve(name, default)
    src = os.environ if env is None else env
    v = src.get(name)
    if v is None or not str(v).strip():
        return default
    return int(str(v).strip())


def env_float(name, default=_UNSET, env=None):
    default = _resolve(name, default)
    src = os.environ if env is None else env
    v = src.get(name)
    if v is None or not str(v).strip():
        return default
    return float(str(v).strip())


def env_bool(name, default=_UNSET, env=None):
    default = _resolve(name, default)
    src = os.environ if env is None else env
    v = src.get(name)
    if v is None:
        return bool(default)
    return str(v).strip() not in ("", "0")


# ---------------------------------------------------------------------------
# The knob catalogue. Section names become docs/env.md headings; keep
# docs to ONE line — gen_env_docs renders them into a table cell.

# -- serving -----------------------------------------------------------
declare("PT_SERVE_PIPELINE", False,
        "Run the scheduler pump one step deep (launch step N+1 before "
        "reading step N's results).", kind="bool", section="serving")
declare("PT_SERVE_TIMELINE", True,
        "Per-request timeline + SLO accounting plane (0 disables; "
        "token outputs are identical either way).",
        kind="bool", section="serving")
declare("PT_SERVE_PULSE", True,
        "Pulse telemetry plane: ring time-series, /debug/pulse, "
        "anomaly capture bundles (0 disables).",
        kind="bool", section="serving")
declare("PT_SERVE_TIMING", False,
        "Attach a timing block (e2e/ttft/phase split) to HTTP "
        "completion responses.", kind="bool", section="serving")
declare("PT_SERVE_RAGGED", True,
        "Serve through the unified ragged step (0 falls back to the "
        "padded batch step).", kind="bool", section="serving")
declare("PT_SERVE_LEAN", True,
        "Lean epilogue: gather only host-read rows before lm_head "
        "(no (T, vocab) logits buffer).", kind="bool", section="serving")
declare("PT_SERVE_TOKBUF", True,
        "Device token ring: keep emitted tokens on device between "
        "steps (0 ships every token).", kind="bool", section="serving")
declare("PT_FAULTS", "",
        "Fault-injection plan spec, e.g. 'crash@step:p=0.01;seed=7' "
        "(empty disables; see serving/faults.py).",
        kind="str", section="serving")
declare("PT_ANOMALY_FLOOR_S", 0.05,
        "Step-stall anomaly sentinel: absolute floor of the "
        "slow-step threshold in seconds.",
        kind="float", section="serving")
declare("PT_COMPILE_CACHE", "",
        "Directory for the persistent XLA compile cache (empty "
        "disables persistence).", kind="str", section="serving")

# -- SLO targets -------------------------------------------------------
declare("PT_SLO_*_TTFT_S", None,
        "Per-class time-to-first-token budget override in seconds "
        "(defaults: INTERACTIVE 1.0, BATCH 10.0).",
        kind="float", section="slo")
declare("PT_SLO_*_TPOT_S", None,
        "Per-class time-per-output-token budget override in seconds "
        "(defaults: INTERACTIVE 0.1, BATCH 1.0).",
        kind="float", section="slo")

# -- pulse plane -------------------------------------------------------
declare("PT_PULSE_DEPTH", 240,
        "Ring depth (samples kept) per pulse signal.",
        kind="int", section="pulse")
declare("PT_PULSE_INTERVAL_S", 1.0,
        "Pulse sampler tick interval in seconds.",
        kind="float", section="pulse")
declare("PT_PULSE_SLO_BURST", 3,
        "SLO-violation burst (per tick) that trips an anomaly "
        "capture.", kind="int", section="pulse")
declare("PT_CAPTURE_DIR", "",
        "Directory for anomaly capture bundles (empty disables "
        "capture).", kind="str", section="pulse")
declare("PT_CAPTURE_MAX", 8,
        "Maximum capture bundles kept on disk (oldest pruned).",
        kind="int", section="pulse")
declare("PT_CAPTURE_MIN_S", 30.0,
        "Minimum seconds between capture bundles (rate limit).",
        kind="float", section="pulse")

# -- fleet plane -------------------------------------------------------
declare("PT_FLEET_HB_S", 0.5,
        "Fleet worker heartbeat interval in seconds.",
        kind="float", section="fleet")
declare("PT_FLEET_HB_MISS_S", 3.0,
        "Heartbeat stall after which the router declares a worker "
        "dead.", kind="float", section="fleet")
declare("PT_FLEET_CALL_TIMEOUT_S", 30.0,
        "Fleet control-plane rpc call timeout in seconds.",
        kind="float", section="fleet")
declare("PT_FLEET_RETRIES", 2,
        "Retries for idempotent fleet control-plane calls.",
        kind="int", section="fleet")
declare("PT_FLEET_FETCH_TIMEOUT_S", 1.0,
        "Per-page budget for prefix-page fetch-on-miss in seconds.",
        kind="float", section="fleet")
declare("PT_FLEET_FETCH_MAX", 8,
        "Maximum prefix pages fetched from peers per local tier "
        "match.", kind="int", section="fleet")
declare("PT_FLEET_SPILL_QUEUE", 128,
        "Bound of the evicted-page spill queue (full queue drops, "
        "never blocks).", kind="int", section="fleet")
declare("PT_FLEET_CLOCK_ALPHA", 0.2,
        "EWMA smoothing factor for per-worker clock-offset "
        "estimation (0 < alpha <= 1; higher tracks faster).",
        kind="float", section="fleet")
declare("PT_FLEET_OBS_POLL_S", 1.0,
        "Router-side fleet observability poll interval in seconds "
        "(worker trigger totals + clock samples).",
        kind="float", section="fleet")
declare("PT_FLEET_CAPTURE_DIR", "",
        "Directory for fleet capture bundles pulled by rank 0 on a "
        "worker pulse trigger (empty disables).",
        kind="str", section="fleet")
declare("PT_FLEET_CAPTURE_MAX", 8,
        "Maximum fleet capture bundles written per router process.",
        kind="int", section="fleet")
declare("PT_FLEET_CAPTURE_MIN_S", 30.0,
        "Minimum seconds between fleet capture bundles (rate limit).",
        kind="float", section="fleet")

# -- observability -----------------------------------------------------
declare("PADDLE_TPU_FLIGHT", True,
        "Flight recorder ring on/off (only the literal '0' "
        "disables).", kind="bool", section="observability")
declare("PADDLE_TPU_FLIGHT_EVENTS", 4096,
        "Flight recorder ring capacity in events.",
        kind="int", section="observability")
declare("PADDLE_TPU_FLIGHT_DIR", "/tmp",
        "Directory flight-recorder dumps are written to.",
        kind="str", section="observability")
declare("PADDLE_TPU_LOG", False,
        "Mirror structured log events to stderr when set to '1'.",
        kind="bool", section="observability")
declare("PADDLE_TPU_LOG_FILE", "",
        "Append structured log events to this file (empty disables).",
        kind="str", section="observability")
declare("PADDLE_TPU_TRACE", False,
        "Lightweight call tracing for debugging when set to '1'.",
        kind="bool", section="observability")
declare("PADDLE_TPU_PROFILE_DIR", "/tmp/pt_profile",
        "Output directory for profiler traces.",
        kind="str", section="observability")
declare("PADDLE_TPU_DEVICE_COST", "1",
        "Device cost model: '0' off, '1' on, 'full' adds per-op "
        "detail.", kind="str", section="observability")
declare("PADDLE_TPU_GEN", "",
        "TPU generation override for the cost model (e.g. 'v5e'); "
        "empty auto-detects.", kind="str", section="observability")
declare("PADDLE_TPU_PEAK_FLOPS", None,
        "Peak FLOP/s override for MFU math (default: per-generation "
        "table).", kind="float", section="observability")
declare("PADDLE_TPU_PEAK_BW", None,
        "Peak HBM bandwidth override in bytes/s for roofline math "
        "(default: per-generation table).",
        kind="float", section="observability")
declare("PADDLE_TPU_RETRACE_WARN", 8,
        "Retrace count per function after which compile telemetry "
        "warns.", kind="int", section="observability")
declare("PT_COMPILE_CACHE_HIT_S", 0.05,
        "Compile wall time below which a compile counts as a "
        "persistent-cache hit.", kind="float", section="observability")

# -- kernels / tuning --------------------------------------------------
declare("PT_DISABLE_PALLAS", False,
        "Force the pure-jnp reference paths instead of Pallas "
        "kernels when '1'.", kind="bool", section="kernels")
declare("PT_FLASH_BLOCK_Q", 128,
        "Flash attention query tile size.", kind="int",
        section="kernels")
declare("PT_FLASH_BLOCK_K", 128,
        "Flash attention key/value tile size.", kind="int",
        section="kernels")
declare("PT_RAGGED_BLOCK_Q", None,
        "Ragged paged-attention query tile override (0 derives the "
        "seed shape; default: tuned per generation).",
        kind="int", section="kernels")
declare("PT_RAGGED_BLOCK_PAGES", None,
        "Ragged paged-attention pages-per-step override (default: "
        "tuned per generation).", kind="int", section="kernels")
declare("PT_RAGGED_TILE_FILE", "",
        "Path of the persisted per-generation ragged kernel tile "
        "table (default: TUNED.kernels.json in the repo).",
        kind="str", section="kernels")
declare("PT_FUSED_CE", False,
        "Fused cross-entropy in the training step when '1'.",
        kind="bool", section="kernels")

# -- distributed -------------------------------------------------------
declare("PT_RPC_BIND", "127.0.0.1",
        "Interface the rpc/bulk servers bind to.",
        kind="str", section="distributed")
declare("PT_RPC_TIMEOUT_S", None,
        "Default rpc_sync timeout in seconds (unset: wait forever, "
        "matching the reference).", kind="float", section="distributed")
declare("PT_RPC_THREADS", 8,
        "Worker threads per rpc agent (serve + callback pools).",
        kind="int", section="distributed")
declare("PT_PS_ENDPOINTS", "",
        "Comma-separated parameter-server endpoints.",
        kind="str", section="distributed")
declare("PT_PS_RANK", 0,
        "This process's rank in the parameter-server world.",
        kind="int", section="distributed")
declare("PT_PS_ROLE", "worker",
        "Parameter-server role of this process ('worker' or "
        "'pserver').", kind="str", section="distributed")
declare("PT_PS_BACKEND", "python",
        "Parameter-server transport backend.",
        kind="str", section="distributed")
declare("PT_PS_CKPT_DIR", "",
        "Parameter-server checkpoint directory (empty disables).",
        kind="str", section="distributed")

# -- io / checkpoint ---------------------------------------------------
declare("PT_DATALOADER_PROCS", False,
        "Use process workers (not threads) in the DataLoader when "
        "'1'.", kind="bool", section="io")
declare("PT_MP_SHM_BYTES", 1 << 30,
        "Shared-memory cache cap in bytes for multiprocessing tensor "
        "reductions.", kind="int", section="io")
declare("PT_AUTO_CKPT_DIR", "",
        "Auto-checkpoint output directory (empty disables the "
        "plane).", kind="str", section="io")
declare("PT_JOB_ID", "default",
        "Job id auto-checkpoint state is keyed under.",
        kind="str", section="io")
declare("PT_CKPT_SAVE_INTER", 900,
        "Auto-checkpoint save interval in seconds.",
        kind="int", section="io")
