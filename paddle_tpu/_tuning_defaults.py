"""Single source of truth for tunable flash-kernel defaults and the
effective-config normalizer.

Used by three consumers that must agree byte-for-byte:
  * paddle_tpu/ops/flash_attention.py — actual kernel block defaults
  * tools/autotune.py                 — trial dedup key
  * tests/test_perf_guard.py          — history grouping key

Deliberately a leaf module with no jax imports; tools/ and tests/ load
it by file path (importlib) to avoid paying for paddle_tpu/__init__.
"""
import os

DEFAULT_FLASH_BLOCK_Q = 128
DEFAULT_FLASH_BLOCK_K = 128


def flash_block_q():
    return int(os.environ.get("PT_FLASH_BLOCK_Q", DEFAULT_FLASH_BLOCK_Q))


def flash_block_k():
    return int(os.environ.get("PT_FLASH_BLOCK_K", DEFAULT_FLASH_BLOCK_K))


def effective_knobs(entry):
    """Normalize a history row / trial cfg dict to its EFFECTIVE tuning
    knobs: absent/None block sizes mean the kernel defaults, and
    absent/0/None n_micro all mean no gradient accumulation."""
    return (int(entry.get("block_q") or DEFAULT_FLASH_BLOCK_Q),
            int(entry.get("block_k") or DEFAULT_FLASH_BLOCK_K),
            int(entry.get("n_micro") or 0))
