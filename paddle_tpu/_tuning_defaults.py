"""Single source of truth for tunable kernel defaults and the
effective-config normalizer.

Used by consumers that must agree byte-for-byte:
  * paddle_tpu/ops/flash_attention.py — flash kernel block defaults
  * paddle_tpu/models/llama_serving.py — serving ragged-kernel tile
  * tools/autotune.py / tools/tune_ragged.py — trial dedup / persist
  * tests/test_perf_guard.py          — history grouping key

Deliberately a leaf module with no jax imports; tools/ and tests/ load
it by file path (importlib) to avoid paying for paddle_tpu/__init__.
The serving engine passes the device generation string IN (resolved
via observability.device_telemetry) so this module stays jax-free.
"""
import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_FLASH_BLOCK_Q = 128
DEFAULT_FLASH_BLOCK_K = 128

# ragged paged-attention serving kernel tile (0 = derive: the GQA
# group sublane-padded / one page per grid step — the seed shape)
DEFAULT_RAGGED_BLOCK_Q = 0
DEFAULT_RAGGED_BLOCK_PAGES = 1

# per-TPU-generation winners persisted by tools/tune_ragged.py; the
# engine loads this ONCE at construction (a static tile — no serving-
# time retrace). Smoke runs must point PT_RAGGED_TILE_FILE elsewhere.
RAGGED_TILE_FILE = os.environ.get("PT_RAGGED_TILE_FILE") or \
    os.path.join(_ROOT, "TUNED.kernels.json")


def flash_block_q():
    return int(os.environ.get("PT_FLASH_BLOCK_Q", DEFAULT_FLASH_BLOCK_Q))


def flash_block_k():
    return int(os.environ.get("PT_FLASH_BLOCK_K", DEFAULT_FLASH_BLOCK_K))


def generation_key(device_kind):
    """Stable slug for a jax `device_kind` string ('TPU v5 lite' ->
    'tpu-v5-lite', 'cpu' -> 'cpu') — the per-generation key tuned
    kernel tiles persist under."""
    s = str(device_kind or "cpu").strip().lower()
    s = "".join(c if c.isalnum() else " " for c in s)
    return "-".join(s.split()) or "cpu"


def load_ragged_tile(device_kind, path=None):
    """Effective (block_q, block_pages) for the serving ragged kernel:
    env override > persisted per-generation winner > builtin default.
    0 means 'derive the seed shape' throughout. Never raises — a
    missing/corrupt tile file silently falls back to the builtins (a
    serving engine must come up on an untuned chip)."""
    bq, bp = DEFAULT_RAGGED_BLOCK_Q, DEFAULT_RAGGED_BLOCK_PAGES
    try:
        with open(path or RAGGED_TILE_FILE) as f:
            entry = (json.load(f).get("ragged") or {}).get(
                generation_key(device_kind)) or {}
        bq = int(entry.get("block_q", bq))
        bp = int(entry.get("block_pages", bp))
    except (OSError, ValueError, TypeError):
        pass
    bq = int(os.environ.get("PT_RAGGED_BLOCK_Q", bq))
    bp = int(os.environ.get("PT_RAGGED_BLOCK_PAGES", bp))
    return bq, bp


def save_ragged_tile(device_kind, block_q, block_pages, path=None,
                     extra=None):
    """Atomically merge one generation's winning tile into the tile
    file (read-modify-write via os.replace, the TUNED.json idiom) and
    return the written entry."""
    path = path or RAGGED_TILE_FILE
    data = {}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    entry = {"block_q": int(block_q), "block_pages": int(block_pages)}
    if extra:
        entry.update(extra)
    data.setdefault("ragged", {})[generation_key(device_kind)] = entry
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return entry


def effective_knobs(entry):
    """Normalize a history row / trial cfg dict to its EFFECTIVE tuning
    knobs: absent/None block sizes mean the kernel defaults, and
    absent/0/None n_micro all mean no gradient accumulation."""
    return (int(entry.get("block_q") or DEFAULT_FLASH_BLOCK_Q),
            int(entry.get("block_k") or DEFAULT_FLASH_BLOCK_K),
            int(entry.get("n_micro") or 0))
