"""AMP (reference: python/paddle/amp/*).

TPU-native: bf16 is the native mixed-precision dtype (no loss scaling
needed); fp16 + dynamic GradScaler kept for API/behavior parity. The
white/black lists mirror amp_lists.py: matmul/conv run in low precision,
reductions/norms/softmax stay fp32.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from .._core import dtypes as _dt
from .._core.state import amp_state
from .._core.tensor import Tensor
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

WHITE_LIST = {"matmul", "mm", "bmm", "conv1d", "conv2d", "conv3d", "linear",
              "einsum", "flash_attention", "scaled_dot_product_attention"}
BLACK_LIST = {"softmax", "log_softmax", "layer_norm", "batch_norm", "rms_norm",
              "cross_entropy", "mean", "sum", "exp", "log", "logsumexp",
              "group_norm", "instance_norm"}


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="float16", use_promote=True):
    st = amp_state()
    prev = (st.amp_dtype, st.amp_level, st.amp_custom_white, st.amp_custom_black)
    if enable:
        st.amp_dtype = _dt.convert_dtype(dtype)
        st.amp_level = level
        st.amp_custom_white = set(custom_white_list or ())
        st.amp_custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        st.amp_dtype, st.amp_level, st.amp_custom_white, st.amp_custom_black = prev


autocast = auto_cast


def is_auto_cast_enabled():
    return amp_state().amp_dtype is not None


def get_amp_dtype():
    d = amp_state().amp_dtype
    return _dt.dtype_name(d) if d is not None else "float32"


def amp_cast_inputs(name, args):
    """Dispatch-time cast used by the op layer when autocast is active."""
    st = amp_state()
    if st.amp_dtype is None:
        return args
    white = (WHITE_LIST | st.amp_custom_white) - st.amp_custom_black
    if st.amp_level == "O2":
        target = st.amp_dtype if name not in (BLACK_LIST | st.amp_custom_black) \
            else _dt.float32
    elif name in white:
        target = st.amp_dtype
    elif name in (BLACK_LIST | st.amp_custom_black):
        target = _dt.float32
    else:
        return args
    out = []
    for a in args:
        if isinstance(a, Tensor) and _dt.is_floating_point_dtype(a.dtype) and \
                a.dtype != target:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """paddle.amp.decorate: O2 casts model params to the amp dtype."""
    d = _dt.convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        excluded = excluded_layers or []
        from ..nn.layer.norm import _BatchNormBase, LayerNorm, _InstanceNormBase
        norm_types = (_BatchNormBase, LayerNorm, _InstanceNormBase)
        for m in model_list:
            for _, layer in m.named_sublayers(include_self=True):
                if isinstance(layer, norm_types) or \
                        any(isinstance(layer, e) for e in excluded
                            if isinstance(e, type)):
                    continue
                for pname, p in layer._parameters.items():
                    if p is not None and _dt.is_floating_point_dtype(p.dtype):
                        p._replace(p._value.astype(d))
    if optimizers is None:
        return models if not isinstance(models, (list, tuple)) else model_list
    opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
    for o in opt_list:
        o._multi_precision = True
    if isinstance(models, (list, tuple)) or isinstance(optimizers, (list, tuple)):
        return model_list, opt_list
    return models, optimizers


def is_float16_supported(device=None):
    """reference: amp/__init__.py — device fp16 capability. XLA:TPU
    computes fp16 (though bf16 is the native fast path); CPU reports
    False like the reference."""
    import jax
    return jax.default_backend() != "cpu"


def is_bfloat16_supported(device=None):
    """bf16 is TPU-native (MXU accumulates bf16 inputs in fp32)."""
    return True
# full debugging module (DebugMode / TensorCheckerConfig / op stats);
# import explicitly — a plain `from . import` would be skipped if any
# attribute named `debugging` already existed
import paddle_tpu.amp.debugging as _debugging_mod  # noqa: E402

debugging = _debugging_mod
