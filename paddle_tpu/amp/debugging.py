"""paddle.amp.debugging (reference: python/paddle/amp/debugging.py):
numeric-health tooling for mixed-precision runs — nan/inf checks,
per-op stats collection, accuracy comparison between runs.

Tape-native: op stats come from counting recorded TapeNodes; the tensor
checker validates op outputs as they are recorded. `check_numerics` is
traced-code-safe: on a traced value it defers to
`observability.health.traced_check` (async count into
`pt_train_nonfinite_total`, no host sync); eager values keep
raise-on-bad semantics with one batched transfer.
"""
from __future__ import annotations

import contextlib
import enum
from dataclasses import dataclass, field

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap

__all__ = [
    "DebugMode", "TensorCheckerConfig", "check_numerics",
    "enable_operator_stats_collection", "disable_operator_stats_collection",
    "collect_operator_stats", "enable_tensor_checker",
    "disable_tensor_checker", "compare_accuracy", "check_layer_numerics",
]


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_ABORT = 4
    CHECK_ALL_ABORT_STOP = 5
    DUMP_ALL = 6


@dataclass
class TensorCheckerConfig:
    enable: bool = False
    debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT
    output_dir: str | None = None
    checked_op_list: list = field(default_factory=list)
    skipped_op_list: list = field(default_factory=list)
    debug_step: tuple | None = None
    stack_height_limit: int = 1


_checker: TensorCheckerConfig | None = None
_op_stats: dict | None = None


def check_numerics(tensor, op_type="", var_name="", debug_mode=None,
                   stack_height_limit=1):
    """Raise if the tensor contains nan/inf (reference check_numerics).

    Routed through the observability health layer: a TRACED value
    (inside jit / to_static) gets the jit-safe fused check —
    `health.traced_check` reports non-finite counts asynchronously via
    `jax.debug.callback` into `pt_train_nonfinite_total` and the flight
    recorder, with no host sync in the step's critical path (the old
    np.asarray + int(bad.sum()) here was exactly tpulint TPL001). An
    EAGER value keeps raise-on-bad semantics, but via one fused device
    reduction + ONE batched transfer instead of three numpy round
    trips over the full array."""
    import jax

    v = unwrap(tensor)
    name = var_name or "tensor"
    if isinstance(v, jax.core.Tracer):
        from ..observability.health import traced_check
        traced_check(v, name=f"check_numerics:{name}")
        return tensor
    vj = jnp.asarray(v)
    if not jnp.issubdtype(vj.dtype, jnp.floating):
        return tensor
    nan_c, inf_c = map(int, jax.device_get(
        (jnp.sum(jnp.isnan(vj)), jnp.sum(jnp.isinf(vj)))))
    if nan_c or inf_c:
        from ..observability.health import HEALTH
        HEALTH.note_nonfinite(nan_c + inf_c, where=f"check_numerics:{name}",
                              source="eager", op=op_type or None)
        raise FloatingPointError(
            f"check_numerics: {nan_c + inf_c}/{vj.size} non-finite values "
            f"in {name}"
            f"{f' (op {op_type})' if op_type else ''}: "
            f"nan={nan_c} inf={inf_c}")
    return tensor


def _record_op(name, outputs):
    """Called by the tape on every recorded op (see _core/tensor._apply)."""
    if _op_stats is not None:
        _op_stats[name] = _op_stats.get(name, 0) + 1
    if _checker is not None and _checker.enable:
        if _checker.checked_op_list and name not in _checker.checked_op_list:
            return
        if name in (_checker.skipped_op_list or ()):
            return
        for o in outputs:
            arr = np.asarray(o)
            if not np.issubdtype(arr.dtype, np.floating):
                continue
            if not np.isfinite(arr).all():
                msg = (f"tensor checker: op '{name}' produced non-finite "
                       f"values")
                if _checker.debug_mode in (
                        DebugMode.CHECK_NAN_INF_AND_ABORT,
                        DebugMode.CHECK_ALL_ABORT,
                        DebugMode.CHECK_ALL_ABORT_STOP):
                    raise FloatingPointError(msg)
                print(f"[amp.debugging] {msg}")


def _sync_observer():
    from .._core import tensor as _t
    _t._op_observer = _record_op if (_op_stats is not None or
                                     _checker is not None) else None


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = {}
    _sync_observer()


def disable_operator_stats_collection():
    global _op_stats
    stats = _op_stats or {}
    _op_stats = None
    _sync_observer()
    if stats:
        print("op".ljust(28), "calls")
        for k in sorted(stats, key=stats.get, reverse=True):
            print(k.ljust(28), stats[k])
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def enable_tensor_checker(config: TensorCheckerConfig):
    global _checker
    config.enable = True
    _checker = config
    _sync_observer()


def disable_tensor_checker():
    global _checker
    _checker = None
    _sync_observer()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1.0, dump_all_tensors=False):
    """Compare two runs' saved tensor dumps (npz dirs) and write a report
    (reference compares fp16 vs fp32 run dumps). Inputs: two .npz
    archives of named tensors."""
    if not (dump_path.endswith(".npz") and
            another_dump_path.endswith(".npz")):
        raise ValueError(
            "compare_accuracy: pass two .npz tensor dumps (save runs with "
            "np.savez); directory dumps are not supported in this build")
    a = np.load(dump_path)
    b = np.load(another_dump_path)
    lines = []
    if a is not None and b is not None:
        for k in sorted(set(a.files) & set(b.files)):
            diff = float(np.max(np.abs(a[k].astype(np.float64) -
                                       b[k].astype(np.float64))))
            lines.append(f"{k}\tmax_abs_diff={diff:.3e}")
    with open(output_filename, "w") as f:
        f.write("\n".join(lines) + "\n")
    return lines


def check_layer_numerics(func):
    """Decorator: validate a Layer forward's inputs/outputs are finite."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                check_numerics(a, var_name=f"input{i}")
        out = func(self, *args, **kwargs)
        for i, o in enumerate(out if isinstance(out, (tuple, list))
                              else [out]):
            if isinstance(o, Tensor):
                check_numerics(o, var_name=f"output{i}")
        return out
    return wrapper
