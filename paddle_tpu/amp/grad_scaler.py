"""GradScaler (reference: python/paddle/amp/grad_scaler.py).

Dynamic loss scaling for fp16; bf16 path is a no-op (TPU-native default).

Found-inf telemetry: every overflow-skipped step reports to
`observability.health` (`pt_amp_found_inf_total`, a flight-recorder
`health` record, and a structured-log warning), so a run quietly
backing its loss scale off is visible on `/metrics` instead of only
in the loss curve. The overflow check itself is ONE fused device
reduction + one transfer per unscale, not one `bool()` sync per param.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._found_inf_steps = 0   # lifetime skipped-step count

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax
        inv = 1.0 / self._scale
        unscaled = []
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                unscaled.append((p, p.grad._value.astype(jnp.float32) * inv))
        if not unscaled:
            self._found_inf = False
            return
        # one fused finite check over every grad, ONE transfer — the
        # per-param bool(jnp.all(...)) here was a sync per parameter
        bad = jnp.zeros((), jnp.int32)
        for _, g in unscaled:
            bad = bad + jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
        found = bool(int(jax.device_get(bad)))
        for p, g in unscaled:
            p.grad = Tensor(g.astype(p.grad.dtype))
        self._found_inf = found
        if found:
            self._found_inf_steps += 1
            from ..observability.health import HEALTH
            HEALTH.note_found_inf(self._scale)

    def minimize(self, optimizer, loss):
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    @property
    def found_inf_steps(self):
        """Lifetime count of overflow-skipped steps (telemetry)."""
        return self._found_inf_steps

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                old = self._scale
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
                from ..observability.logging import get_logger
                get_logger("health").event(
                    "health.amp_scale_backoff", level="warning",
                    old_scale=old, new_scale=self._scale)
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state_dict):
        self._scale = state_dict.get("scale", self._scale)
        self._good_steps = state_dict.get("incr_count", 0)
        self._bad_steps = state_dict.get("decr_count", 0)


AmpScaler = GradScaler
