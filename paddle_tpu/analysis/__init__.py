"""tpulint — TPU-hostility static analysis for paddle_tpu.

The classic ways a JAX/TPU program gets slow are invisible in a diff:
a `.numpy()` deep in the decode loop silently serializes the pipeline,
a Python branch on a traced value retraces per shape, an `np.random`
call inside a jitted body breaks determinism, a lock held across a
device call stalls every other thread. tpulint is an AST pass that
catches these *classes* at review time, over the whole tree, with no
runtime or profile needed.

Usage (library):

    from paddle_tpu.analysis import lint_paths
    findings, nfiles = lint_paths(["paddle_tpu/"])

Usage (CLI):

    python tools/tpulint.py paddle_tpu/ [--format json]

Rules (see docs/static_analysis.md for bad/good examples):

  TPL001  host-sync in a hot path        (error)
  TPL002  retrace hazard in jitted code  (warning)
  TPL003  untraced randomness            (error)
  TPL004  lock discipline in serving/    (warning)
  TPL005  eager block_until_ready        (warning)
  TPL006  mutable default / import-time device allocation (error)

Cross-file rules (the tpuracer pass — a whole-program index of thread
entries, per-class locks, acquisition order, and attribute ownership
is built first, then each finding lands at its single witness line):

  TPL007  lock-order inversion across files            (error)
  TPL008  multi-thread shared write, no common lock    (error)
  TPL009  blocking socket/rpc/queue call under a lock  (error)
  TPL010  env knob read but not declared in _env.py    (error)
  TPL011  pt_* metric booked/documented drift          (warning)

Suppress a reviewed finding inline with a justification:

    x = np.asarray(lengths)  # tpulint: disable=TPL001 -- host-side table

or on the line above (`# tpulint: disable-next-line=TPL001 -- why`),
or file-wide (`# tpulint: disable-file=TPL002 -- why`).
"""
from __future__ import annotations

from .engine import Finding, Rule, Severity, all_rules, get_rule, register
from .config import LintConfig, DEFAULT_CONFIG
from .project import ProjectIndex
from .runner import analyze_paths, lint_file, lint_paths, lint_source
from .reporting import render_json, render_text

# importing .rules registers every built-in rule with the engine
from . import rules as _rules  # noqa: F401  (registration side effect)

__all__ = [
    "Finding", "Rule", "Severity", "LintConfig", "DEFAULT_CONFIG",
    "ProjectIndex", "all_rules", "get_rule", "register",
    "analyze_paths", "lint_file", "lint_paths", "lint_source",
    "render_json", "render_text",
]
