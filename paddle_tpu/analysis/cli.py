"""tpulint command line (the body of tools/tpulint.py).

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage/config error.
Deliberately importable without jax — the linter is pure stdlib ast,
so CI boxes without an accelerator stack can run it.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .config import LintConfig
from .engine import all_rules, get_rule
from .reporting import render_json, render_text
from .runner import analyze_paths

# rule registration side effect
from . import rules as _rules  # noqa: F401


def _select_rules(only, disable):
    selected = all_rules()
    if only:
        wanted = {r.strip().upper() for r in only.split(",") if r.strip()}
        _validate(wanted)
        selected = [r for r in selected if r.id in wanted]
    if disable:
        dropped = {r.strip().upper() for r in disable.split(",")
                   if r.strip()}
        _validate(dropped)
        selected = [r for r in selected if r.id not in dropped]
    return selected


def _validate(ids):
    known = {r.id for r in all_rules()}
    unknown = ids - known
    if unknown:
        raise SystemExit(
            f"tpulint: unknown rule(s) {sorted(unknown)}; "
            f"known: {sorted(known)}")


def _changed_since(ref):
    """Absolute paths of files changed since `ref` (diff + untracked),
    for the --changed fast mode. Raises ValueError on git trouble."""
    def _git(*args):
        proc = subprocess.run(["git", *args], capture_output=True,
                              text=True, timeout=30)
        if proc.returncode != 0:
            raise ValueError(
                f"git {' '.join(args)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        return proc.stdout

    top = _git("rev-parse", "--show-toplevel").strip()
    names = _git("diff", "--name-only", "--diff-filter=d",
                 ref).splitlines()
    names += _git("ls-files", "--others",
                  "--exclude-standard").splitlines()
    return {os.path.abspath(os.path.join(top, n))
            for n in names if n.strip()}


def _threads_text(project):
    rows = project.thread_report()
    out = ["thread entries (threading.Thread registrations):"]
    if not rows:
        out.append("  (none found in the scanned files)")
    width_name = max([len(r[0]) for r in rows], default=4) + 2
    width_entry = max([len(r[1]) for r in rows], default=5) + 2
    for hint, entry, where in rows:
        out.append(f"  {hint:<{width_name}}{entry:<{width_entry}}{where}")
    out.append("")
    out.append("plus the <caller> pseudo-entry: any external thread "
               "reaching the public API methods.")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="TPU-hostility static analysis for paddle_tpu "
                    "(host syncs, retrace hazards, untraced RNG, lock "
                    "discipline, import-time device work, cross-file "
                    "lock order / thread ownership / registry drift)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", metavar="TPL001,TPL002",
                    help="run only these rules")
    ap.add_argument("--disable", metavar="TPL005",
                    help="skip these rules")
    ap.add_argument("--config", metavar="FILE.json",
                    help="JSON overlay for hot modules / bench paths / "
                         "lock scope / severities")
    ap.add_argument("--changed", metavar="GIT_REF",
                    help="report findings only for files changed since "
                         "this git ref (the project index still covers "
                         "every scanned file) — fast pre-commit mode")
    ap.add_argument("--threads", action="store_true",
                    help="print the inferred thread-entry inventory "
                         "from the project index and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in text output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}  {r.severity.value:7s} {r.name}")
            print(f"        {r.rationale}")
        return 0

    if not args.paths:
        ap.error("no paths given (try: tpulint paddle_tpu/)")

    try:
        config = LintConfig.from_json(args.config) if args.config \
            else LintConfig.default()
        rules = _select_rules(args.rules, args.disable)
        changed = _changed_since(args.changed) if args.changed else None
    except (OSError, ValueError) as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2

    findings, nfiles, project = analyze_paths(args.paths, config=config,
                                              rules=rules)
    if args.threads:
        print(_threads_text(project))
        return 0
    if changed is not None:
        findings = [f for f in findings
                    if os.path.abspath(f.path) in changed]
    if args.format == "json":
        print(render_json(findings, nfiles))
    else:
        print(render_text(findings, nfiles,
                          show_suppressed=args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
