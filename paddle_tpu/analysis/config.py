"""Repo-level lint configuration.

The interesting judgement calls — *which* non-jitted code counts as a
hot path, *where* an eager `block_until_ready` is legitimate, *which*
packages get lock-discipline analysis — live here rather than in the
rules, so a deployment can retarget tpulint with a JSON file instead
of forking rule code (`tools/tpulint.py --config my.json`).

All patterns are `fnmatch` globs matched against the forward-slash
path of the scanned file (both the full path and every suffix of it,
so `serving/*.py` matches `/root/repo/paddle_tpu/serving/scheduler.py`).
"""
from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass, field

from .engine import Severity


def _match(patterns, path):
    p = path.replace("\\", "/")
    parts = p.split("/")
    cands = {p} | {"/".join(parts[i:]) for i in range(len(parts))}
    return any(fnmatch.fnmatch(c, pat) for pat in patterns for c in cands)


@dataclass
class LintConfig:
    # Modules whose plain (non-jit) functions still count as hot for
    # TPL001's host-sync checks: the serving runtime's step/pump loops
    # run per decode step, so a stray device->host pull there costs a
    # tunnel round trip per token.
    hot_modules: list = field(default_factory=list)
    # function (or Class.method) names inside hot_modules that form
    # the actual per-step loop; empty = every function in the module.
    hot_functions: list = field(default_factory=list)
    # Where an eager block_until_ready is the *point* (benchmarks,
    # profilers, device warm-up) rather than a pipeline stall.
    bench_paths: list = field(default_factory=list)
    # Packages that get TPL004 lock-discipline analysis.
    lock_scope: list = field(default_factory=list)
    # Files skipped entirely.
    exclude: list = field(default_factory=list)
    # Per-rule severity overrides: {"TPL002": "info"}.
    severity: dict = field(default_factory=dict)
    # The sanctioned async result reader(s) of the serving pump loop:
    # the ONLY functions in a hot module allowed to call
    # jax.device_get. TPL001 skips findings inside them AND flags any
    # device_get in a hot module outside them — the pipelined pump's
    # invariant ("one batched read, issued a step behind") is enforced
    # by lint, not convention.
    sanctioned_sync: list = field(default_factory=list)
    # Packages whose classes/threads enter the whole-program project
    # index (TPL007 lock order, TPL008 ownership, TPL009 blocking).
    concurrency_scope: list = field(default_factory=list)
    # Dotted-name fnmatch patterns of calls that block on the network
    # or a queue — TPL009 flags them under a held lock.
    blocking_calls: list = field(default_factory=list)
    # Lock attr-name globs that exist to serialize one IO channel
    # (socket write mutexes); TPL009 ignores them by design.
    io_locks: list = field(default_factory=list)
    # Packages migrated to the paddle_tpu._env accessors: TPL010 bans
    # raw os.environ reads of declared knobs there.
    env_migrated: list = field(default_factory=list)
    # Glob patterns (relative to the invocation cwd) of the markdown
    # files holding the pt_* metric tables TPL011 cross-checks.
    metrics_docs: list = field(default_factory=list)

    # ---- queries used by the rules -----------------------------------
    def is_hot_module(self, path):
        return _match(self.hot_modules, path)

    def is_hot_function(self, qualname):
        """qualname is 'func' or 'Class.method'."""
        if not self.hot_functions:
            return True
        leaf = qualname.rsplit(".", 1)[-1]
        return any(fnmatch.fnmatch(qualname, pat)
                   or fnmatch.fnmatch(leaf, pat)
                   for pat in self.hot_functions)

    def is_bench_path(self, path):
        return _match(self.bench_paths, path)

    def is_sanctioned_sync(self, qualname):
        """qualname is 'func' or 'Class.method' — the async result
        reader(s) allowed to device_get in the pump loop."""
        leaf = qualname.rsplit(".", 1)[-1]
        return any(fnmatch.fnmatch(qualname, pat)
                   or fnmatch.fnmatch(leaf, pat)
                   for pat in self.sanctioned_sync)

    def in_lock_scope(self, path):
        return _match(self.lock_scope, path)

    def in_concurrency_scope(self, path):
        return _match(self.concurrency_scope, path)

    def in_env_migrated(self, path):
        return _match(self.env_migrated, path)

    def is_excluded(self, path):
        return _match(self.exclude, path)

    def severity_for(self, rule_id, default):
        s = self.severity.get(rule_id)
        return Severity.parse(s) if s is not None else default

    # ---- construction -------------------------------------------------
    @classmethod
    def default(cls):
        return cls(
            hot_modules=[
                "paddle_tpu/serving/*.py",
                "paddle_tpu/models/llama_serving.py",
                # the pulse plane samples on a daemon thread riding the
                # scrape cadence — a device pull there would serialize
                # against the pump's dispatch stream just the same
                "paddle_tpu/observability/pulse.py",
                # fleet observability runs on router/worker daemon
                # threads between rpc round trips — same rule
                "paddle_tpu/observability/fleet_obs.py",
            ],
            hot_functions=[
                # ServingEngine per-token loop + its helpers
                "ServingEngine.step", "ServingEngine._spec_step",
                "ServingEngine._prefill_step", "ServingEngine._admit",
                "ServingEngine._seed_first_token",
                # device-side sampler + pipelined step pair (ROADMAP
                # item 4): these ARE the per-token hot loop now
                "ServingEngine.step_launch", "ServingEngine.step_finish",
                "ServingEngine.run_pipelined",
                "ServingEngine._note_launch_gap",
                # unified ragged step: flat descriptor builder + its
                # finish twin are the default per-wave hot loop
                "ServingEngine._ragged_launch",
                "ServingEngine._ragged_finish",
                "ServingEngine._bucket_for",
                # lean epilogue (ISSUE 12): the spec rejection
                # sampler's lazy distribution-row pull runs inside the
                # acceptance loop — sync discipline applies (its one
                # read rides _fetch_results)
                "ServingEngine._spec_row_dist",
                # disaggregated handoff (ISSUE 13): harvest runs once
                # per step; export/import move KV pages through the
                # kvtier copy thread's explicit fences — their device
                # transfers must never look like a stray sync
                "ServingEngine._harvest_handoffs",
                "ServingEngine._export_handoff",
                "ServingEngine._import_handoff",
                # scheduler pump + publish run once per engine step
                "RequestScheduler._pump", "RequestScheduler._publish",
                "RequestScheduler._feed_locked",
                "RequestScheduler._step_pipelined",
                "RequestScheduler._finish_pending",
                "RequestScheduler._drain_needed",
                # timeline/SLO plane (ISSUE 14): host-clock-only by
                # contract — marks stamp on the pump and engine loops,
                # finalize judges SLOs, the sentinel's note() runs per
                # step. None of these may ever touch the device.
                "Timeline.mark", "Timeline.count",
                "Timeline.segments", "Timeline.phases",
                "StepAnomalySentinel.note",
                "RequestScheduler._finalize",
                "RequestScheduler._account_slo",
                "RequestScheduler._timeline_entry",
                # pulse plane (ISSUE 15): sampler + bundle writer run
                # on the pulse/scrape threads against host-side
                # snapshots only — zero device syncs by lint, so the
                # observability plane can never stall the pump
                "PulseSampler.sample",
                "PulsePlane.tick",
                "PulsePlane._check_triggers",
                "PulsePlane._write_bundle",
                "RequestScheduler._pulse_snapshot",
                "RequestScheduler._book_depth_locked",
                # fleet plane (ISSUE 16): the bulk-channel serving
                # threads stream tokens and ship exported (host-side
                # numpy) KV pages per request, and the spill/fetch pair
                # runs on the kvtier path — all must stay pure
                # host+socket code with zero device pulls
                "FleetWorker._serve_stream",
                "FleetWorker._serve_handoff",
                "FleetPages._spill_loop",
                "FleetPages.fetch_missing",
                "RemoteRequest._read_loop",
                # fleet observability: the obs poll loop + the pull
                # paths it drives run per tick on the router, and the
                # estimator update runs per rpc reply
                "ClockSkewEstimator.sample",
                "FleetWorker.obs_snapshot",
                "FleetPlane._obs_loop",
                "FleetPlane.obs_sections",
            ],
            bench_paths=[
                "bench*.py", "tools/*.py", "tests/*.py", "examples/*.py",
                "paddle_tpu/profiler/*.py", "paddle_tpu/utils/__init__.py",
                "paddle_tpu/device/*.py",
            ],
            lock_scope=["paddle_tpu/serving/*.py"],
            exclude=[],
            severity={},
            # the engine's batched reader is the one sanctioned
            # device->host sync of the whole step loop
            sanctioned_sync=["ServingEngine._fetch_results"],
            # the thread-heavy planes: serving runtime + the
            # observability daemons that scrape it
            concurrency_scope=[
                "paddle_tpu/serving/*.py",
                "paddle_tpu/observability/*.py",
            ],
            blocking_calls=[
                # raw socket ops (wire.py and friends)
                "*.sendall", "*.recv", "*.recv_into", "*.accept",
                "*.connect", "*.create_connection",
                # rpc layer round trips
                "rpc_sync", "*.rpc_sync",
                "*.store.get", "*.store.set", "*.store.wait",
                "*.all_worker_infos",
                # stdlib network fetches
                "*.urlopen",
            ],
            io_locks=["*_wlock", "*_send_lock", "*_io_lock"],
            env_migrated=[
                "paddle_tpu/serving/*.py",
                "paddle_tpu/observability/*.py",
            ],
            metrics_docs=["docs/*.md"],
        )

    @classmethod
    def from_json(cls, path):
        """Overlay a JSON config file onto the defaults; list fields
        replace, the severity dict merges."""
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        cfg = cls.default()
        list_keys = ("hot_modules", "hot_functions", "bench_paths",
                     "lock_scope", "exclude", "sanctioned_sync",
                     "concurrency_scope", "blocking_calls", "io_locks",
                     "env_migrated", "metrics_docs")
        for key in list_keys:
            if key in data:
                setattr(cfg, key, list(data[key]))
        if "severity" in data:
            cfg.severity.update(data["severity"])
        unknown = set(data) - set(list_keys) - {"severity"}
        if unknown:
            raise ValueError(f"tpulint config: unknown keys {sorted(unknown)}")
        return cfg


DEFAULT_CONFIG = LintConfig.default()
