"""Per-file analysis context: one parse, shared by every rule.

Builds the pieces rules keep needing:

  * parent links on every AST node (`node._tpul_parent`);
  * the set of function defs that are *traced* — decorated with
    jit/pjit/pmap/shard_map/to_static (directly or via
    functools.partial), wrapped by a module-level `g = jax.jit(f)`,
    or nested inside such a function;
  * per-function qualnames ('Class.method') for the hot-function
    config match;
  * import aliases, so `import jax.numpy as jnp` and
    `from numpy import asarray` both resolve to canonical roots.
"""
from __future__ import annotations

import ast


_TRACE_WRAPPERS = {
    "jit", "pjit", "pmap", "shard_map", "to_static", "checkpoint",
    "remat", "grad", "value_and_grad", "vmap",
}


def dotted_name(node):
    """ast expr -> 'a.b.c' when it is a plain attribute chain, else ''."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _decorator_traces(dec):
    """True when a decorator expression compiles the function body.

    Handles `@jax.jit`, `@jit`, `@pt.jit.to_static`,
    `@functools.partial(jax.jit, static_argnames=...)`, and
    `@jax.jit(...)`-style calls.
    """
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn.rsplit(".", 1)[-1] == "partial":
            return any(_decorator_traces(a) for a in dec.args)
        dec_name = fn
    else:
        dec_name = dotted_name(dec)
    leaf = dec_name.rsplit(".", 1)[-1] if dec_name else ""
    return leaf in _TRACE_WRAPPERS


class FileContext:
    def __init__(self, path, source, config):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.config = config
        self.tree = ast.parse(source, filename=path)
        # the whole-program index (analysis.project.ProjectIndex); the
        # runner attaches it after every file has parsed, so cross-file
        # rules see the full picture while per-file rules ignore it
        self.project = None
        self._link_parents()
        self.import_aliases = self._scan_imports()
        self.traced_functions = self._find_traced_functions()
        self.qualnames = self._build_qualnames()

    # -------------------------------------------------------------- infra
    def line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def _link_parents(self):
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._tpul_parent = parent

    def parents(self, node):
        p = getattr(node, "_tpul_parent", None)
        while p is not None:
            yield p
            p = getattr(p, "_tpul_parent", None)

    def enclosing_function(self, node):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return None

    # ------------------------------------------------------------ imports
    def _scan_imports(self):
        """alias -> canonical dotted root, e.g. {'jnp': 'jax.numpy',
        'np': 'numpy', 'asarray': 'numpy.asarray'}."""
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if node.module:
                        aliases[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
                    elif node.level:
                        # `from . import wire as _wire` — the sibling
                        # module itself is the canonical root
                        aliases[a.asname or a.name] = a.name
        return aliases

    def resolve(self, node):
        """Canonical dotted name of a call target / attribute chain,
        with the leading alias expanded: `jnp.zeros` -> 'jax.numpy.zeros'."""
        name = dotted_name(node)
        if not name:
            return ""
        head, _, rest = name.partition(".")
        root = self.import_aliases.get(head, head)
        return f"{root}.{rest}" if rest else root

    # ----------------------------------------------------- traced regions
    def _find_traced_functions(self):
        traced = set()
        # by decorator
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_traces(d) for d in node.decorator_list):
                    traced.add(node)
        # by wrapping call anywhere in the module: g = jax.jit(f) /
        # self._step = jax.jit(step, donate_argnums=...)
        defs = {n.name: n for n in ast.walk(self.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = self.resolve(node.func).rsplit(".", 1)[-1]
            if leaf not in _TRACE_WRAPPERS and leaf != "partial":
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    if leaf in _TRACE_WRAPPERS:
                        traced.add(defs[arg.id])
        # nested defs inherit the traced context
        out = set(traced)
        for fn in traced:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.add(sub)
        return out

    def in_traced_code(self, node):
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced_functions:
                return fn
            fn = self.enclosing_function(fn)
        return None

    # --------------------------------------------------------- qualnames
    def _build_qualnames(self):
        quals = {}

        def visit(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    quals[child] = ".".join(stack + [child.name])
                    visit(child, stack + [child.name])
                elif isinstance(child, ast.ClassDef):
                    visit(child, stack + [child.name])
                else:
                    visit(child, stack)

        visit(self.tree, [])
        return quals

    def qualname(self, fn):
        return self.qualnames.get(fn, getattr(fn, "name", "<module>"))

    def in_hot_function(self, node):
        """Innermost named function, when this file is a configured hot
        module and the function matches the hot-function list."""
        if not self.config.is_hot_module(self.path):
            return None
        fn = self.enclosing_function(node)
        if fn is None:
            return None
        return fn if self.config.is_hot_function(self.qualname(fn)) else None

    # ------------------------------------------------------------ helpers
    def function_params(self, fn):
        a = fn.args
        names = [p.arg for p in
                 a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    def expr_mentions_shape(self, node):
        """Does the expression read `.shape`/`.ndim`/`.size` or call
        len()? — the static-but-shape-dependent values whose use in
        Python control flow means one retrace per distinct shape."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in ("shape", "ndim", "size"):
                return True
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and sub.func.id == "len":
                return True
        return False

    def expr_mentions_param(self, node, params):
        """Does the expression (transitively) read one of `params`,
        other than through .shape/.ndim/len()?"""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in params:
                parent = getattr(sub, "_tpul_parent", None)
                if isinstance(parent, ast.Attribute) and \
                        parent.attr in ("shape", "ndim", "size", "dtype"):
                    continue
                if isinstance(parent, ast.Call) and \
                        isinstance(parent.func, ast.Name) and \
                        parent.func.id == "len":
                    continue
                return True
        return False
