"""Rule engine core: findings, severities, the rule registry, and the
inline-suppression grammar.

A rule is a class with a `check(ctx) -> iterable[Finding]` method over
one parsed file (`context.FileContext`). Registration is declarative —
defining a subclass with `@register` adds it to the global table the
runner and CLI iterate.
"""
from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    @classmethod
    def parse(cls, s):
        try:
            return cls(str(s).lower())
        except ValueError:
            raise ValueError(
                f"unknown severity {s!r}: want one of "
                f"{[m.value for m in cls]}") from None


@dataclass
class Finding:
    """One diagnostic. `line`/`col` are 1-based/0-based like CPython's
    ast; `context` is the stripped source line for human output."""
    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str
    context: str = ""
    suppressed: bool = False
    suppress_reason: str = ""

    def to_dict(self):
        d = {
            "rule": self.rule,
            "severity": self.severity.value,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "context": self.context,
        }
        if self.suppressed:
            d["suppressed"] = True
            if self.suppress_reason:
                d["suppress_reason"] = self.suppress_reason
        return d


class Rule:
    """Base class. Subclasses set `id` (TPLnnn), `name`, `severity`,
    and a one-line `rationale` used by --list-rules and the docs."""
    id: str = ""
    name: str = ""
    severity: Severity = Severity.WARNING
    rationale: str = ""

    def check(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError

    def finding(self, ctx, node, message, severity=None):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=self.id,
            severity=severity or ctx.config.severity_for(self.id,
                                                         self.severity),
            path=ctx.path,
            line=line,
            col=col,
            message=message,
            context=ctx.line(line).strip(),
        )


_REGISTRY: dict = {}


def register(cls):
    """Class decorator: add a Rule subclass to the global registry."""
    if not cls.id or not re.fullmatch(r"TPL\d{3}", cls.id):
        raise ValueError(f"rule {cls.__name__}: id {cls.id!r} must be TPLnnn")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules():
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id):
    return _REGISTRY[rule_id]


# ---------------------------------------------------------------- suppression
# Grammar (comment anywhere on the physical line):
#   # tpulint: disable=TPL001[,TPL004|all] [-- justification]
#   # tpulint: disable-next-line=TPL001 [-- justification]
#   # tpulint: disable-file=TPL002 [-- justification]
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable(?:-next-line|-file)?)\s*=\s*"
    r"([A-Za-z0-9_,\s]+?)\s*(?:--\s*(.*))?$")


@dataclass
class Suppressions:
    """Per-file index of inline suppressions."""
    by_line: dict = field(default_factory=dict)       # line -> (set, reason)
    file_wide: set = field(default_factory=set)
    file_reasons: dict = field(default_factory=dict)

    @classmethod
    def scan(cls, source_lines):
        sup = cls()
        for i, text in enumerate(source_lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, ids_s, reason = m.group(1), m.group(2), m.group(3) or ""
            ids = {t.strip().upper() for t in ids_s.split(",") if t.strip()}
            if kind == "disable-file":
                sup.file_wide |= ids
                for r in ids:
                    sup.file_reasons[r] = reason
            else:
                line = i + 1 if kind == "disable-next-line" else i
                cur, old_reason = sup.by_line.get(line, (set(), ""))
                sup.by_line[line] = (cur | ids, reason or old_reason)
        return sup

    def match(self, finding):
        """Return (suppressed, reason) for a finding."""
        if "ALL" in self.file_wide or finding.rule in self.file_wide:
            return True, self.file_reasons.get(
                finding.rule, self.file_reasons.get("ALL", ""))
        ids, reason = self.by_line.get(finding.line, (set(), ""))
        if "ALL" in ids or finding.rule in ids:
            return True, reason
        return False, ""


def apply_suppressions(findings, suppressions):
    for f in findings:
        hit, reason = suppressions.match(f)
        if hit:
            f.suppressed = True
            f.suppress_reason = reason
    return findings
