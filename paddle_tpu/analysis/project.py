"""Whole-program project index — the cross-file phase of tpulint.

Per-file rules (TPL001–TPL006) see one AST at a time; the concurrency
and contract rules introduced with the tpuracer pass (TPL007–TPL011)
need the *project*: which functions run on which threads, which locks
exist per class, in what order code acquires them, who writes each
shared attribute, which env knobs are declared, which metrics are
booked and documented. `ProjectIndex.build(contexts, config)` derives
all of it in one pass over the already-parsed `FileContext`s, and the
rules then filter the index's findings down to the file they are
checking (a cross-file finding is emitted only by the file holding its
witness line, so every finding appears exactly once and inline
suppressions keep working).

The index is deliberately conservative where static analysis runs out
of road: attribute types come only from `self.x = ClassName(...)`
assignments, call targets resolve only through `self.m()` /
`self.attr.m()` / same-file bare calls, and anything unresolvable
simply contributes nothing (no guessed findings).
"""
from __future__ import annotations

import ast
import fnmatch
import glob
import os
import re

from .context import dotted_name


# metric names inside a backtick in a docs table row: full names,
# optional {a,b} alternation groups, optional * wildcards
_DOC_TOKEN_RE = re.compile(r"`(pt_[a-z0-9_{},*]+)`")
# exposition-style literal: the metric name followed by a space or a
# label brace *inside the same string* ("pt_mfu {v}" f-strings etc.)
_EXPO_RE = re.compile(r"^(pt_[a-z0-9_]+)[ {]")
_PT_NAME_RE = re.compile(r"^pt_[a-z0-9_]+$")

_ENV_ACCESSORS = {"env_raw", "env_str", "env_int", "env_float",
                  "env_bool"}
_METRIC_KINDS = {"counter", "gauge", "histogram"}
_LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

CALLER_ENTRY = "<caller>"


def pretty_key(key):
    """Human form of a method-table key: class methods are already
    'Class.m'; module functions turn 'dir/wire.py::send_msg' into
    'wire.send_msg'."""
    if "::" not in key:
        return key
    path, _, name = key.partition("::")
    mod = os.path.basename(path)
    if mod.endswith(".py"):
        mod = mod[:-3]
    return f"{mod}.{name}"


def _self_attr(node):
    """'attr' when node is `self.attr`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def env_knob_name(name):
    """True when `name` is a paddle_tpu-owned env knob (the namespaces
    TPL010 governs)."""
    return name.startswith("PT_") or name.startswith("PADDLE_TPU_")


class ThreadEntry:
    """One inferred thread entry point: a `threading.Thread(target=…)`
    registration, or the shared `<caller>` pseudo-entry standing for
    every external thread that can call public API methods."""

    def __init__(self, entry_id, target_key, name_hint, path, line):
        self.entry_id = entry_id      # human id, e.g. 'Sched._pump'
        self.target_key = target_key  # method-table key or None
        self.name_hint = name_hint    # thread name= kwarg, best effort
        self.path = path
        self.line = line

    def __repr__(self):  # pragma: no cover - debug aid
        return f"ThreadEntry({self.entry_id!r}, name={self.name_hint!r})"


class WriteSite:
    def __init__(self, cls_name, attr, locks, node, path, method):
        self.cls_name = cls_name
        self.attr = attr
        self.locks = frozenset(locks)  # lock ids held at the write
        self.node = node
        self.path = path
        self.method = method           # method-table key


class CallSite:
    def __init__(self, dotted, target_key, locks, node, path,
                 blocking_desc=None):
        self.dotted = dotted           # raw dotted text, '' if exotic
        self.target_key = target_key   # resolved method key or None
        self.locks = frozenset(locks)
        self.node = node
        self.path = path
        self.blocking_desc = blocking_desc  # str when the call blocks


class AcqEdge:
    """Lock-order edge: `dst` acquired while `src` is held."""

    def __init__(self, src, dst, node, path, detail):
        self.src = src
        self.dst = dst
        self.node = node
        self.path = path
        self.detail = detail


class MethodSummary:
    def __init__(self, key, cls_name, name, path, node):
        self.key = key
        self.cls_name = cls_name       # None for module functions
        self.name = name
        self.path = path
        self.node = node
        self.writes = []               # [WriteSite]
        self.reads = []                # [(attr, locks)]
        self.calls = []                # [CallSite]
        self.direct_acquires = set()   # lock ids acquired lexically
        self.acq_edges = []            # [AcqEdge] direct nestings


class ClassIndex:
    def __init__(self, name, path, node):
        self.name = name
        self.path = path
        self.node = node
        self.locks = set()             # self attrs holding Lock/Cond
        self.attr_types = {}           # attr -> leaf class/type name
        self.methods = {}              # method name -> MethodSummary

    def lock_id(self, attr):
        return f"{self.name}.{attr}"


class ProjectIndex:
    """Cross-file facts + the lazily computed cross-file analyses."""

    def __init__(self, config):
        self.config = config
        self.classes = {}              # class name -> ClassIndex
        self.methods = {}              # method key -> MethodSummary
        self.thread_entries = []       # [ThreadEntry] (real threads)
        self.env_declared = set()      # exact knob names
        self.env_patterns = []         # knob names containing '*'
        self.env_registry_paths = []   # the scanned _env.py files
        self.metric_bookings = []      # [(name, node, path)] registry calls
        self.metric_tokens = set()     # permissive: any pt_* literal
        self.metric_token_patterns = set()  # f-string bookings, '*'-holed
        self.metrics_registry_path = None  # file defining MetricsRegistry
        self.docs_names = None         # {name: docfile} | None (no docs)
        self.docs_patterns = []        # [(fnmatch pat, docfile)]
        self._mod_funcs = {}           # (module, func) -> method key
        self._cycles = None
        self._races = None
        self._blocking = None
        self._trans_acquires = None

    # ------------------------------------------------------------ build
    @classmethod
    def build(cls, contexts, config):
        idx = cls(config)
        scoped = [c for c in contexts
                  if config.in_concurrency_scope(c.path)]
        for ctx in contexts:
            idx._scan_contracts(ctx)
        for ctx in scoped:
            idx._scan_classes(ctx)
        # attr types need the full class table, so resolve them (and
        # everything depending on call resolution) in a second pass
        for ctx in scoped:
            idx._scan_bodies(ctx)
        idx._load_docs()
        return idx

    # ---- pass 0: env + metrics contracts (all files) -----------------
    def _scan_contracts(self, ctx):
        if os.path.basename(ctx.path) == "_env.py":
            self.env_registry_paths.append(ctx.path)
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == "declare" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    if "*" in name:
                        self.env_patterns.append(name)
                    else:
                        self.env_declared.add(name)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name == "MetricsRegistry":
                self.metrics_registry_path = ctx.path
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _METRIC_KINDS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("pt_"):
                    name = arg.value
                    self.metric_bookings.append((name, node, ctx.path))
                    self.metric_tokens.add(name)
                elif isinstance(arg, ast.JoinedStr):
                    # dynamic name, e.g. f"pt_phase_{ph}_seconds":
                    # remember the shape so documented rows expanding
                    # to it don't read as ghosts
                    pat = _const_prefix(arg)
                    if pat and pat.startswith("pt_"):
                        self.metric_token_patterns.add(pat)
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                self._note_metric_token(node.value)

    def _note_metric_token(self, s):
        if _PT_NAME_RE.match(s):
            self.metric_tokens.add(s)
        else:
            m = _EXPO_RE.match(s)
            if m:
                self.metric_tokens.add(m.group(1))

    def _load_docs(self):
        files = sorted({f for pat in self.config.metrics_docs
                        for f in glob.glob(pat)})
        if not files:
            return
        self.docs_names = {}
        for path in files:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            for line in text.splitlines():
                if not line.lstrip().startswith("|"):
                    continue
                for tok in _DOC_TOKEN_RE.findall(line):
                    for name in _expand_braces(tok):
                        if "*" in name:
                            self.docs_patterns.append((name, path))
                        else:
                            self.docs_names[name] = path

    # ---- metric name matching (the _total render tolerance) ----------
    @staticmethod
    def _names_equal(a, b):
        return a == b or a + "_total" == b or a == b + "_total"

    def metric_documented(self, booked):
        if self.docs_names is None:
            return True
        for doc in self.docs_names:
            if self._names_equal(booked, doc):
                return True
        return any(fnmatch.fnmatch(booked, pat)
                   or fnmatch.fnmatch(booked + "_total", pat)
                   for pat, _ in self.docs_patterns)

    def undocumented_bookings(self):
        return [(name, node, path)
                for name, node, path in self.metric_bookings
                if not self.metric_documented(name)]

    def unbooked_documented(self):
        """Doc-table names with no trace in code — only meaningful when
        the scan actually includes the metrics registry."""
        if self.docs_names is None or self.metrics_registry_path is None:
            return []
        out = []
        for doc, docfile in sorted(self.docs_names.items()):
            if any(self._names_equal(doc, tok)
                   for tok in self.metric_tokens):
                continue
            if any(fnmatch.fnmatch(doc, pat)
                   for pat in self.metric_token_patterns):
                continue
            out.append((doc, docfile))
        return out

    # ---- env registry queries ----------------------------------------
    def env_is_declared(self, name):
        if name in self.env_declared:
            return True
        return any(fnmatch.fnmatch(name, pat)
                   for pat in self.env_patterns)

    @property
    def has_env_registry(self):
        return bool(self.env_registry_paths)

    # ---- pass 1: class skeletons (scoped files) ----------------------
    def _scan_classes(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = self.classes.setdefault(
                node.name, ClassIndex(node.name, ctx.path, node))
            for m in node.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                key = f"{node.name}.{m.name}"
                ms = MethodSummary(key, node.name, m.name, ctx.path, m)
                ci.methods[m.name] = ms
                self.methods[key] = ms
                for sub in ast.walk(m):
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Call):
                        leaf = ctx.resolve(sub.value.func) \
                            .rsplit(".", 1)[-1]
                        for t in sub.targets:
                            attr = _self_attr(t)
                            if attr is None:
                                continue
                            if leaf in _LOCK_TYPES:
                                ci.locks.add(attr)
                            elif leaf:
                                ci.attr_types.setdefault(attr, leaf)
        # module-level functions get summaries too (thread targets and
        # call-graph hops go through them: wire.send_msg etc.)
        mod = os.path.basename(ctx.path)[:-3]
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{ctx.path}::{node.name}"
                ms = MethodSummary(key, None, node.name, ctx.path, node)
                self.methods[key] = ms
                # imported cross-file calls resolve through the leading
                # module name ('wire.send_msg' / `from .wire import
                # send_msg`); last definition wins on collisions
                self._mod_funcs[(mod, node.name)] = key

    # ---- pass 2: bodies (needs full class/attr-type table) -----------
    def _scan_bodies(self, ctx):
        # class methods
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name in self.classes:
                ci = self.classes[node.name]
                if ci.path != ctx.path:
                    continue  # duplicate class name in another file
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and \
                            m.name in ci.methods:
                        self._scan_method(ctx, ci, ci.methods[m.name])
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{ctx.path}::{node.name}"
                if key in self.methods:
                    self._scan_method(ctx, None, self.methods[key])

    def _scan_method(self, ctx, ci, ms):
        m = ms.node
        for node in ast.walk(m):
            held = self._locks_held(ctx, node, ci, m)
            if isinstance(node, (ast.Assign, ast.AugAssign)) and \
                    ci is not None:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None or attr in ci.locks:
                        continue
                    ms.writes.append(WriteSite(
                        ci.name, attr, held, node, ctx.path, ms.key))
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and ci is not None:
                attr = _self_attr(node)
                if attr and attr not in ci.locks:
                    ms.reads.append((attr, frozenset(held)))
            elif isinstance(node, ast.With):
                for item in node.items:
                    lock = self._with_lock(ctx, item, ci)
                    if lock is None:
                        continue
                    ms.direct_acquires.add(lock)
                    for h in held:
                        if h != lock:
                            ms.acq_edges.append(AcqEdge(
                                h, lock, node, ctx.path,
                                f"`with` in `{ms.key}`"))
            elif isinstance(node, ast.Call):
                self._scan_call(ctx, ci, ms, node, held)

    def _scan_call(self, ctx, ci, ms, node, held):
        dotted = dotted_name(node.func)
        resolved = ctx.resolve(node.func)
        target_key = self._resolve_call(ctx, ci, dotted, resolved)
        blocking = self._blocking_desc(ctx, ci, node, dotted)
        ms.calls.append(CallSite(dotted, target_key, held, node,
                                 ctx.path, blocking))
        # thread entry registration
        if resolved in ("threading.Thread", "Thread"):
            self._note_thread(ctx, ci, node)

    def _note_thread(self, ctx, ci, node):
        target = name_hint = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "name":
                name_hint = _const_prefix(kw.value)
        if target is None:
            return
        entry_id, key = self._entry_for(ctx, ci, target)
        self.thread_entries.append(ThreadEntry(
            entry_id, key, name_hint or "", ctx.path, node.lineno))

    def _entry_for(self, ctx, ci, target):
        """(human id, method key or None) for a Thread target expr."""
        attr = _self_attr(target)
        if attr is not None and ci is not None:
            if attr in ci.methods:
                return f"{ci.name}.{attr}", f"{ci.name}.{attr}"
            return f"{ci.name}.{attr}", None
        if isinstance(target, ast.Name):
            key = f"{ctx.path}::{target.id}"
            return target.id, key if key in self.methods else None
        dotted = dotted_name(target)
        if dotted.startswith("self.") and ci is not None:
            # self.attr.method — type the attr if we can
            parts = dotted.split(".")
            if len(parts) == 3:
                tcls = ci.attr_types.get(parts[1])
                if tcls in self.classes and \
                        parts[2] in self.classes[tcls].methods:
                    key = f"{tcls}.{parts[2]}"
                    return key, key
        return dotted or "<unresolved>", None

    # ---- lock / call helpers -----------------------------------------
    def _locks_held(self, ctx, node, ci, method):
        """Lock ids held lexically at `node` within `method`; methods
        named *_locked document "caller holds the lock" and count as
        holding every lock of their class."""
        if ci is None:
            return frozenset()
        held = set()
        if method.name.endswith("_locked"):
            held |= {ci.lock_id(a) for a in ci.locks}
        for p in ctx.parents(node):
            if p is method:
                break
            if isinstance(p, ast.With):
                for item in p.items:
                    lock = self._with_lock(ctx, item, ci)
                    if lock is not None:
                        held.add(lock)
        return frozenset(held)

    def _with_lock(self, ctx, item, ci):
        """Lock id when a `with` item acquires a class lock."""
        if ci is None:
            return None
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value     # self._cv.acquire_timeout(...)
        attr = _self_attr(expr)
        if attr is not None and attr in ci.locks:
            return ci.lock_id(attr)
        return None

    def _resolve_call(self, ctx, ci, dotted, resolved=""):
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] == "self" and ci is not None:
            if len(parts) == 2 and parts[1] in ci.methods:
                return f"{ci.name}.{parts[1]}"
            if len(parts) == 3:
                tcls = ci.attr_types.get(parts[1])
                if tcls in self.classes and \
                        parts[2] in self.classes[tcls].methods:
                    return f"{tcls}.{parts[2]}"
            return None
        if len(parts) == 1:
            key = f"{ctx.path}::{parts[0]}"
            if key in self.methods:
                return key
        # imported module function: `send_msg` resolving to
        # 'wire.send_msg', or a direct `wire.send_msg(...)` call
        rparts = (resolved or dotted).split(".")
        if len(rparts) >= 2:
            return self._mod_funcs.get((rparts[-2], rparts[-1]))
        return None

    def _blocking_desc(self, ctx, ci, node, dotted):
        """Why this call can block (str), else None. Driven by the
        `blocking_calls` config patterns plus a queue.get special case
        (only a get with no timeout parks the thread forever)."""
        cand = dotted or ""
        resolved = ctx.resolve(node.func)
        for pat in self.config.blocking_calls:
            if (cand and fnmatch.fnmatch(cand, pat)) or \
                    (resolved and fnmatch.fnmatch(resolved, pat)):
                return cand or resolved
        # self._q.get() on a queue.Queue-typed attr, no timeout
        parts = cand.split(".")
        if len(parts) == 3 and parts[0] == "self" and \
                parts[2] == "get" and ci is not None:
            tleaf = ci.attr_types.get(parts[1], "")
            if tleaf.endswith("Queue"):
                has_timeout = len(node.args) >= 2 or any(
                    kw.arg == "timeout" for kw in node.keywords)
                if not has_timeout:
                    return f"{cand} (queue get, no timeout)"
        return None

    # ============================================================ lazy
    # ---- transitive lock acquisition (fixpoint over the call graph)
    def _transitive_acquires(self):
        if self._trans_acquires is not None:
            return self._trans_acquires
        acq = {k: set(m.direct_acquires) for k, m in self.methods.items()}
        changed = True
        while changed:
            changed = False
            for k, m in self.methods.items():
                for call in m.calls:
                    if call.target_key and call.target_key in acq:
                        extra = acq[call.target_key] - acq[k]
                        if extra:
                            acq[k] |= extra
                            changed = True
        self._trans_acquires = acq
        return acq

    def lock_order_edges(self):
        """All acquisition-order edges, direct and through calls."""
        acq = self._transitive_acquires()
        edges = []
        for m in self.methods.values():
            edges.extend(m.acq_edges)
            for call in m.calls:
                if not call.locks or not call.target_key:
                    continue
                for dst in acq.get(call.target_key, ()):
                    for src in call.locks:
                        if src != dst:
                            edges.append(AcqEdge(
                                src, dst, call.node, call.path,
                                f"call into "
                                f"`{pretty_key(call.target_key)}` "
                                f"from `{pretty_key(m.key)}`"))
        return edges

    def lock_cycles(self):
        """Cycles in the lock-order graph; one record per SCC:
        (ordered lock-id cycle, witness AcqEdge)."""
        if self._cycles is not None:
            return self._cycles
        adj = {}
        for e in self.lock_order_edges():
            adj.setdefault(e.src, {})
            # keep the earliest witness per (src, dst)
            cur = adj[e.src].get(e.dst)
            if cur is None or (e.path, e.node.lineno) < \
                    (cur.path, cur.node.lineno):
                adj[e.src][e.dst] = e
        self._cycles = []
        for scc in _sccs({s: set(d) for s, d in adj.items()}):
            if len(scc) < 2:
                continue
            inside = [adj[s][d] for s in scc for d in adj.get(s, {})
                      if d in scc]
            witness = min(inside, key=lambda e: (e.path, e.node.lineno))
            self._cycles.append((sorted(scc), witness))
        return self._cycles

    # ---- thread reachability + shared-attribute ownership -----------
    def entry_points(self):
        """[(entry_id, [start method keys])] — real thread entries plus
        the `<caller>` pseudo-entry for public API methods."""
        entries = {}
        for te in self.thread_entries:
            if te.target_key:
                entries.setdefault(te.entry_id, set()).add(te.target_key)
        public = {ms.key for ms in self.methods.values()
                  if ms.cls_name is not None
                  and not ms.name.startswith("_")}
        if public:
            entries[CALLER_ENTRY] = public
        return sorted((eid, sorted(keys))
                      for eid, keys in entries.items())

    def reachable(self, start_keys):
        seen = set(start_keys)
        stack = list(start_keys)
        while stack:
            k = stack.pop()
            m = self.methods.get(k)
            if m is None:
                continue
            for call in m.calls:
                t = call.target_key
                if t and t not in seen:
                    seen.add(t)
                    stack.append(t)
        return seen

    def ownership_map(self):
        """{(class, attr): {entry_id: [WriteSite]}} over every entry —
        the attribute ownership map TPL008 judges."""
        owners = {}
        for eid, starts in self.entry_points():
            reach = self.reachable(starts)
            for k in reach:
                m = self.methods.get(k)
                if m is None:
                    continue
                if m.name in ("__init__", "__post_init__"):
                    continue  # construction happens-before the threads
                for w in m.writes:
                    owners.setdefault((w.cls_name, w.attr), {}) \
                        .setdefault(eid, []).append(w)
        return owners

    def shared_attr_races(self):
        """TPL008 substance: [(class, attr, entry ids, witness
        WriteSite)] for multi-writer attrs with no common lock."""
        if self._races is not None:
            return self._races
        self._races = []
        for (cls_name, attr), by_entry in sorted(
                self.ownership_map().items()):
            if len(by_entry) < 2:
                continue  # single-writer (delta-mirror) — fine
            sites = [w for sites in by_entry.values() for w in sites]
            common = frozenset.intersection(
                *[w.locks for w in sites])
            if common:
                continue
            witness = min(sites, key=lambda w: (len(w.locks), w.path,
                                                w.node.lineno))
            self._races.append((cls_name, attr,
                                sorted(by_entry), witness))
        return self._races

    # ---- blocking-while-locked (TPL009) ------------------------------
    def _transitive_blocking(self):
        """method key -> one witness blocking desc reachable from it."""
        blk = {}
        for k, m in self.methods.items():
            for call in m.calls:
                if call.blocking_desc:
                    blk.setdefault(k, call.blocking_desc)
        changed = True
        while changed:
            changed = False
            for k, m in self.methods.items():
                if k in blk:
                    continue
                for call in m.calls:
                    t = call.target_key
                    if t and t in blk:
                        blk[k] = f"{blk[t]} via `{pretty_key(t)}`"
                        changed = True
                        break
        return blk

    def blocking_under_lock(self):
        """TPL009 substance: [(desc, locks, CallSite, via)] — blocking
        calls made while holding a non-IO lock, directly or through a
        resolvable callee."""
        if self._blocking is not None:
            return self._blocking
        trans = self._transitive_blocking()
        out = []
        for m in self.methods.values():
            for call in m.calls:
                locks = self._state_locks(call.locks)
                if not locks:
                    continue
                if call.blocking_desc:
                    out.append((call.blocking_desc, locks, call, None))
                elif call.target_key and call.target_key in trans:
                    out.append((trans[call.target_key], locks, call,
                                call.target_key))
        self._blocking = out
        return out

    def _state_locks(self, locks):
        """Drop IO-ownership locks (config `io_locks` name globs): a
        mutex whose *purpose* is serializing one socket legitimately
        spans its sends."""
        kept = []
        for lid in locks:
            attr = lid.rsplit(".", 1)[-1]
            if not any(fnmatch.fnmatch(attr, pat)
                       for pat in self.config.io_locks):
                kept.append(lid)
        return sorted(kept)

    # ---- reporting ---------------------------------------------------
    def thread_report(self):
        """Rows for the CLI --threads inventory: (thread name hint,
        entry, path:line)."""
        rows = []
        for te in sorted(self.thread_entries,
                         key=lambda t: (t.path, t.line)):
            rows.append((te.name_hint or "-", te.entry_id,
                         f"{te.path}:{te.line}"))
        return rows


def _const_prefix(node):
    """Best-effort constant text of a str expr ('pt-fleet-*' for
    f-strings with formatted tails)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.append(v.value)
            else:
                out.append("*")
        return "".join(out)
    return None


def _expand_braces(tok):
    """'pt_a_{x,y}_total' -> ['pt_a_x_total', 'pt_a_y_total']."""
    m = re.search(r"\{([^{}]*,[^{}]*)\}", tok)
    if not m:
        yield tok
        return
    head, tail = tok[:m.start()], tok[m.end():]
    for alt in m.group(1).split(","):
        yield from _expand_braces(head + alt + tail)


def _sccs(adj):
    """Tarjan strongly-connected components of {node: {succ}}."""
    nodes = set(adj) | {d for ds in adj.values() for d in ds}
    index = {}
    low = {}
    onstack = set()
    stack = []
    out = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (the lock graph is tiny, but recursion
        # limits are not worth risking in a linter)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out
