"""Text and JSON renderers for lint results.

JSON schema (stable; tests/test_tpulint.py pins it):

    {
      "version": 1,
      "files_scanned": <int>,
      "findings": [ {rule, severity, path, line, col, message,
                     context, suppressed?, suppress_reason?} ],
      "counts": {"<rule>": <unsuppressed count>},
      "suppressed": <int>,
      "clean": <bool>          # no unsuppressed findings
    }
"""
from __future__ import annotations

import json
from collections import Counter


def _active(findings):
    return [f for f in findings if not f.suppressed]


def render_text(findings, files_scanned, show_suppressed=False):
    out = []
    shown = findings if show_suppressed else _active(findings)
    for f in shown:
        tag = " [suppressed]" if f.suppressed else ""
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                   f"{f.severity.value}: {f.message}{tag}")
        if f.context:
            out.append(f"    {f.context}")
    active = _active(findings)
    counts = Counter(f.rule for f in active)
    summary = ", ".join(f"{r}×{n}" for r, n in sorted(counts.items()))
    nsup = len(findings) - len(active)
    out.append(
        f"tpulint: {len(active)} finding(s) in {files_scanned} file(s)"
        + (f" [{summary}]" if summary else "")
        + (f", {nsup} suppressed" if nsup else ""))
    return "\n".join(out)


def render_json(findings, files_scanned):
    active = _active(findings)
    doc = {
        "version": 1,
        "files_scanned": files_scanned,
        "findings": [f.to_dict() for f in findings],
        "counts": dict(Counter(f.rule for f in active)),
        "suppressed": len(findings) - len(active),
        "clean": not active,
    }
    return json.dumps(doc, indent=2, sort_keys=True)
