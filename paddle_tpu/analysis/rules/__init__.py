"""Built-in tpulint rules. Importing this package registers every rule
with the engine registry (paddle_tpu.analysis.engine)."""
from . import host_sync    # TPL001, TPL005   # noqa: F401
from . import retrace      # TPL002           # noqa: F401
from . import rng          # TPL003           # noqa: F401
from . import locks        # TPL004           # noqa: F401
from . import imports      # TPL006           # noqa: F401
from . import concurrency  # TPL007-TPL009    # noqa: F401
from . import contracts    # TPL010, TPL011   # noqa: F401
