"""TPL007/TPL008/TPL009 — cross-file concurrency rules.

These consume the whole-program `ProjectIndex` (thread entries, lock
inventory, acquisition-order graph, attribute ownership) rather than
the single file's AST; each finding is emitted only by the file that
holds its witness line, so a project-wide hazard is reported exactly
once and an inline suppression at the witness keeps working.

  TPL007  lock-order inversion: the acquisition graph (built from
          lexically nested `with self.lock:` blocks and calls made
          while holding a lock) has a cycle — two threads taking the
          same pair of locks in opposite orders deadlock under load.
  TPL008  shared attribute with multiple writing threads and no
          common lock. Thread entries are `threading.Thread(target=…)`
          registrations plus a `<caller>` pseudo-entry for public API
          methods. Single-writer attrs (the delta-mirror pattern) and
          `__init__` writes are exempt; `*_locked` methods count as
          holding every class lock.
  TPL009  blocking call (socket send/recv/accept, rpc_sync, store
          round-trips, queue.get with no timeout — the config
          `blocking_calls` patterns) while holding a lock: every other
          thread needing that lock stalls for a network round trip.
          Locks named like IO mutexes (config `io_locks`, e.g.
          `*_wlock`) are exempt — serializing one socket is what they
          are *for*.
"""
from __future__ import annotations

from ..engine import Rule, Severity, register
from ..project import pretty_key


def _project(ctx):
    proj = getattr(ctx, "project", None)
    if proj is None or not ctx.config.in_concurrency_scope(ctx.path):
        return None
    return proj


@register
class LockOrderRule(Rule):
    id = "TPL007"
    name = "lock-order-inversion"
    severity = Severity.ERROR
    rationale = ("a cycle in the cross-file lock acquisition graph "
                 "means two threads can take the same locks in "
                 "opposite orders and deadlock under load")

    def check(self, ctx):
        proj = _project(ctx)
        if proj is None:
            return
        for cycle, witness in proj.lock_cycles():
            if witness.path != ctx.path:
                continue
            order = " -> ".join(cycle + [cycle[0]])
            yield self.finding(
                ctx, witness.node,
                f"lock-order inversion: {order} — acquired here via "
                f"{witness.detail}; another path takes them in the "
                "opposite order, so two threads can deadlock. Pick one "
                "global order (or drop to a single lock)")


@register
class SharedWriteRule(Rule):
    id = "TPL008"
    name = "unlocked-shared-write"
    severity = Severity.ERROR
    rationale = ("an attribute written by two or more thread entry "
                 "points with no common lock is a data race the "
                 "moment scheduling changes")

    def check(self, ctx):
        proj = _project(ctx)
        if proj is None:
            return
        for cls_name, attr, entries, witness in \
                proj.shared_attr_races():
            if witness.path != ctx.path:
                continue
            yield self.finding(
                ctx, witness.node,
                f"`self.{attr}` ({cls_name}) is written from "
                f"{len(entries)} thread entries "
                f"({', '.join(entries)}) with no common lock — "
                "guard every write with one lock, or make a single "
                "thread the owner and mirror deltas to it")


@register
class BlockingUnderLockRule(Rule):
    id = "TPL009"
    name = "blocking-call-under-lock"
    severity = Severity.ERROR
    rationale = ("a socket/rpc/queue wait while holding a lock turns "
                 "one slow peer into a stall of every thread that "
                 "needs the lock")

    def check(self, ctx):
        proj = _project(ctx)
        if proj is None:
            return
        for desc, locks, call, via in proj.blocking_under_lock():
            if call.path != ctx.path:
                continue
            how = (f"calls `{pretty_key(via)}` which blocks on "
                   f"`{desc}`") if via else f"blocks on `{desc}`"
            yield self.finding(
                ctx, call.node,
                f"{how} while holding {', '.join(locks)} — do the "
                "I/O outside the lock and publish the result under "
                "it (or rename the lock `*_wlock` if it exists only "
                "to serialize this channel)")
