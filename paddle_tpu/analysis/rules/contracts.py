"""TPL010/TPL011 — registry-drift contract rules.

The tree's two by-convention contracts, made checkable:

  TPL010  every `PT_*` / `PADDLE_TPU_*` env knob the code reads must
          be declared in `paddle_tpu/_env.py` (name, default, doc) —
          and inside the migrated packages (config `env_migrated`)
          reads must go through the `_env` accessors, not raw
          `os.environ`, so defaults and parsing live in exactly one
          place.
  TPL011  every `pt_*` metric booked on the MetricsRegistry must
          appear in the docs tables (config `metrics_docs`), and every
          documented name must still exist in code — dashboards keep
          working, docs never advertise ghosts. Counter exposition
          appends `_total`, so names match with `_total` tolerance.
"""
from __future__ import annotations

import ast
import os

from ..engine import Rule, Severity, register
from ..project import env_knob_name, _ENV_ACCESSORS


def _read_env_name(ctx, node):
    """(knob name, direct) when `node` reads an env var by literal
    name: os.environ.get/[]/in, os.getenv, or an _env accessor."""
    if isinstance(node, ast.Call):
        target = ctx.resolve(node.func)
        leaf = target.rsplit(".", 1)[-1]
        if target in ("os.environ.get", "os.getenv") or \
                target.endswith(".os.environ.get"):
            direct = True
        elif leaf in _ENV_ACCESSORS:
            direct = False
        else:
            return None
        if node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            return node.args[0].value, direct
        return None
    if isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load) and \
            ctx.resolve(node.value) == "os.environ" and \
            isinstance(node.slice, ast.Constant) and \
            isinstance(node.slice.value, str):
        return node.slice.value, True
    if isinstance(node, ast.Compare) and len(node.ops) == 1 and \
            isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
            ctx.resolve(node.comparators[0]) == "os.environ" and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        return node.left.value, True
    return None


@register
class EnvRegistryRule(Rule):
    id = "TPL010"
    name = "env-registry-drift"
    severity = Severity.ERROR
    rationale = ("an env knob read outside the central _env registry "
                 "has no declared default or doc — ops can't discover "
                 "it and two readers drift on parsing")

    def check(self, ctx):
        proj = getattr(ctx, "project", None)
        if proj is None or os.path.basename(ctx.path) == "_env.py":
            return
        migrated = ctx.config.in_env_migrated(ctx.path)
        for node in ast.walk(ctx.tree):
            hit = _read_env_name(ctx, node)
            if hit is None:
                continue
            name, direct = hit
            if not env_knob_name(name):
                continue
            if not proj.env_is_declared(name):
                yield self.finding(
                    ctx, node,
                    f"env knob `{name}` is read here but not declared "
                    "in paddle_tpu/_env.py — add a declare(...) entry "
                    "(default + one-line doc) so docs/env.md stays "
                    "complete")
            elif direct and migrated:
                yield self.finding(
                    ctx, node,
                    f"raw os.environ read of declared knob `{name}` — "
                    "this package is migrated to the registry; use "
                    "paddle_tpu._env.env_str/env_int/env_float/"
                    "env_bool so parsing and defaults stay in one "
                    "place")


@register
class MetricsContractRule(Rule):
    id = "TPL011"
    name = "metrics-contract-drift"
    severity = Severity.WARNING
    rationale = ("a metric booked but not documented is invisible to "
                 "dashboards; one documented but gone breaks them "
                 "silently")

    def check(self, ctx):
        proj = getattr(ctx, "project", None)
        if proj is None or proj.docs_names is None:
            return
        for name, node, path in proj.undocumented_bookings():
            if path != ctx.path:
                continue
            yield self.finding(
                ctx, node,
                f"metric `{name}` is booked here but absent from the "
                "docs tables "
                f"({', '.join(sorted(ctx.config.metrics_docs))}) — "
                "add a row (counters render with a `_total` suffix)")
        # the ghost direction anchors at the registry definition so it
        # is reported exactly once per scan
        if ctx.path == proj.metrics_registry_path:
            for doc, docfile in proj.unbooked_documented():
                yield self.finding(
                    ctx, ctx.tree,
                    f"metric `{doc}` is documented in {docfile} but "
                    "never booked or rendered anywhere in the scanned "
                    "tree — delete the row or restore the metric")
