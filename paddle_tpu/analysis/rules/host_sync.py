"""TPL001 (host-sync in a hot path) and TPL005 (eager
block_until_ready outside bench/profiler code).

A device->host transfer inside compiled or per-step code serializes
the whole pipeline: the host blocks until every queued device
computation retires, then the next step's dispatch starts cold. On
TPU each one is a tunnel round trip; MPK measures throughput lost to
exactly these, not to FLOPs.
"""
from __future__ import annotations

import ast

from ..context import dotted_name
from ..engine import Rule, Severity, register

# Canonical call targets that force a device->host sync.
_SYNC_CALLS = {
    "jax.device_get": "jax.device_get() blocks until the value is on host",
    "numpy.asarray": "np.asarray() on a device value copies it to host",
    "numpy.array": "np.array() on a device value copies it to host",
}
_SYNC_METHODS = {
    "numpy": ".numpy() materializes the value on host",
    "item": ".item() pulls a scalar to host",
    "tolist": ".tolist() pulls the whole array to host",
}


@register
class HostSyncRule(Rule):
    id = "TPL001"
    name = "host-sync-in-hot-path"
    severity = Severity.ERROR
    rationale = ("device->host transfers inside jitted bodies or the "
                 "serving step loop serialize the device pipeline")

    def check(self, ctx):
        flagged = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            traced = ctx.in_traced_code(node)
            hot = None if traced else ctx.in_hot_function(node)
            if traced is None and hot is None:
                continue
            if hot is not None and self._sanctioned(ctx, hot):
                continue  # the configured async result reader
            where = (f"jitted `{traced.name}`" if traced
                     else f"hot path `{ctx.qualname(hot)}`")
            msg = self._classify(ctx, node, traced is not None)
            if msg:
                flagged.add(id(node))
                yield self.finding(ctx, node, f"{msg} (in {where})")
        # config check (sanctioned_sync): in a hot module the
        # sanctioned async result reader is the ONLY place allowed to
        # call jax.device_get — everywhere else, even outside the
        # configured hot functions, a raw device_get is a second host
        # sync the pipelined pump cannot overlap
        if not ctx.config.sanctioned_sync or \
                not ctx.config.is_hot_module(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in flagged:
                continue
            if ctx.resolve(node.func) != "jax.device_get":
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and self._sanctioned(ctx, fn):
                continue
            qn = ctx.qualname(fn) if fn is not None else "<module>"
            yield self.finding(
                ctx, node,
                "jax.device_get() outside the sanctioned async result "
                f"reader (in `{qn}`; config sanctioned_sync = "
                f"{ctx.config.sanctioned_sync}) — route the transfer "
                "through the one batched reader so the pump loop keeps "
                "a single, overlappable host sync")

    @staticmethod
    def _sanctioned(ctx, fn):
        return ctx.config.is_sanctioned_sync(ctx.qualname(fn))

    def _classify(self, ctx, call, in_traced):
        # method-style syncs: x.numpy() / x.item() / x.tolist()
        if isinstance(call.func, ast.Attribute) and not call.args \
                and not call.keywords:
            hit = _SYNC_METHODS.get(call.func.attr)
            if hit:
                return hit
        target = ctx.resolve(call.func)
        hit = _SYNC_CALLS.get(target)
        if hit:
            return hit
        # float()/int() on a traced value concretize it. Only flagged
        # inside traced code, and not for shape/len() arithmetic, which
        # is static under trace.
        if in_traced and isinstance(call.func, ast.Name) \
                and call.func.id in ("float", "int", "bool") \
                and len(call.args) == 1:
            arg = call.args[0]
            fn = ctx.enclosing_function(call)
            params = ctx.function_params(fn) if fn is not None else set()
            if isinstance(arg, ast.Constant):
                return None
            if ctx.expr_mentions_shape(arg):
                return None
            if ctx.expr_mentions_param(arg, params):
                return (f"{call.func.id}() concretizes a traced value "
                        "(aborts tracing or forces a sync)")
        return None


@register
class EagerBlockRule(Rule):
    id = "TPL005"
    name = "eager-block-until-ready"
    severity = Severity.WARNING
    rationale = ("block_until_ready outside bench/profiler code stalls "
                 "async dispatch; XLA already serializes data dependencies")

    def check(self, ctx):
        if ctx.config.is_bench_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                yield self.finding(
                    ctx, node,
                    "block_until_ready() in library code stalls async "
                    "dispatch — only benchmarks/profilers should fence "
                    "the device")
            elif isinstance(node, ast.Call) and \
                    dotted_name(node.func).endswith("block_until_ready"):
                yield self.finding(
                    ctx, node,
                    "jax.block_until_ready() in library code stalls "
                    "async dispatch — only benchmarks/profilers should "
                    "fence the device")
