"""TPL006 — mutable default arguments and import-time device work.

Mutable defaults are the classic shared-state footgun; in a framework
they additionally leak across jit boundaries (the default is part of
the cached signature by identity). Module-level `jnp.*` / device_put
calls initialize the backend at *import* time — they grab the TPU
runtime (or crash in a CPU-only driver process) before the program
chose a platform, and make `import paddle_tpu` cost a device round
trip.
"""
from __future__ import annotations

import ast

from ..engine import Rule, Severity, register

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = ("list", "dict", "set", "bytearray", "defaultdict",
                  "OrderedDict", "deque", "Counter")

# Call roots that allocate on / initialize the device backend.
_DEVICE_ALLOC_PREFIXES = (
    "jax.numpy.", "jax.device_put", "jax.devices", "jax.local_devices",
    "jax.random.", "jax.device_count", "jax.local_device_count",
    "jax.eval_shape",
)
# jnp helpers that are pure metadata (no allocation) — allowed.
_DEVICE_ALLOC_EXEMPT = (
    "jax.numpy.dtype", "jax.numpy.issubdtype", "jax.numpy.promote_types",
    "jax.numpy.finfo", "jax.numpy.iinfo",
)


@register
class ImportHygieneRule(Rule):
    id = "TPL006"
    name = "mutable-default-or-import-time-device-work"
    severity = Severity.ERROR
    rationale = ("mutable defaults alias across calls (and across the "
                 "jit cache); module-level jnp/device calls init the "
                 "backend at import time")

    def check(self, ctx):
        yield from self._check_defaults(ctx)
        yield from self._check_import_time(ctx)

    # -- mutable default args -------------------------------------------
    def _check_defaults(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                continue
            a = node.args
            name = getattr(node, "name", "<lambda>")
            for d in list(a.defaults) + [x for x in a.kw_defaults
                                         if x is not None]:
                if isinstance(d, _MUTABLE_LITERALS):
                    yield self.finding(
                        ctx, d,
                        f"mutable default argument in `{name}`: shared "
                        "across every call — default to None and build "
                        "inside")
                elif isinstance(d, ast.Call) and \
                        isinstance(d.func, ast.Name) and \
                        d.func.id in _MUTABLE_CTORS:
                    yield self.finding(
                        ctx, d,
                        f"mutable default argument `{d.func.id}()` in "
                        f"`{name}`: evaluated once at def time and "
                        "shared — default to None and build inside")

    # -- import-time device allocation ----------------------------------
    def _check_import_time(self, ctx):
        for node in self._import_time_nodes(ctx.tree):
            for sub in self._walk_skipping_lambdas(node):
                if not isinstance(sub, ast.Call):
                    continue
                target = ctx.resolve(sub.func)
                if not target or target in _DEVICE_ALLOC_EXEMPT:
                    continue
                if any(target == p or target.startswith(p)
                       for p in _DEVICE_ALLOC_PREFIXES):
                    yield self.finding(
                        ctx, sub,
                        f"`{target}` at module import time initializes "
                        "the device backend before the program picked "
                        "one — allocate lazily (inside a function or "
                        "cached property)")

    def _walk_skipping_lambdas(self, node):
        """ast.walk, but lambda bodies are deferred (not import time)."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            for child in ast.iter_child_nodes(n):
                if not isinstance(child, ast.Lambda):
                    stack.append(child)

    def _import_time_nodes(self, tree):
        """Statements executed when the module is imported: module and
        class bodies (descending through module-level if/try/with/for),
        but never function bodies. For a def, only its decorators and
        defaults run at import time."""
        stack = [s for s in tree.body]
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    yield dec
                for d in stmt.args.defaults:
                    yield d
                for d in stmt.args.kw_defaults:
                    if d is not None:
                        yield d
            elif isinstance(stmt, ast.ClassDef):
                stack.extend(stmt.body)
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                   ast.Try)):
                for attr in ("body", "orelse", "finalbody"):
                    stack.extend(getattr(stmt, attr, []) or [])
                for h in getattr(stmt, "handlers", []) or []:
                    stack.extend(h.body)
                for sub in ("test", "iter"):
                    node = getattr(stmt, sub, None)
                    if node is not None:
                        yield node
                for item in getattr(stmt, "items", []) or []:
                    yield item.context_expr
            else:
                yield stmt
