"""TPL004 — lock discipline in the serving runtime.

Two hazards in thread-heavy code driving a TPU engine:

  1. a shared attribute written under `self._lock` in one method and
     bare in another — the bare write races the locked readers;
  2. an engine/device call made while holding the lock — a decode
     step is milliseconds of device time, so every submitter blocks
     on the condition variable for the whole step.

Scope is configured (`lock_scope`, default `paddle_tpu/serving/`).
Convention: methods named `*_locked` document "caller holds the
lock" and are treated as locked context.
"""
from __future__ import annotations

import ast

from ..context import dotted_name
from ..engine import Rule, Severity, register

_LOCK_TYPES = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")

# Call leafs that can occupy the device / block for a step while the
# lock is held. `step` and `generate` are the engine entry points.
_BLOCKING_LEAFS = {"step", "generate", "block_until_ready",
                   "device_get", "sleep"}


def _self_attr(node):
    """'attr' when node is `self.attr`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, cls):
        self.cls = cls
        self.locks = set()          # attr names holding a Lock/Condition
        self.locked_writes = {}     # attr -> first write node under lock
        self.bare_writes = []       # (attr, node, method)
        self.locked_calls = []      # (node, method, lock_attr)


@register
class LockDisciplineRule(Rule):
    id = "TPL004"
    name = "lock-discipline"
    severity = Severity.WARNING
    rationale = ("shared attrs written bare race their locked readers; "
                 "engine/device calls under a lock stall every thread "
                 "for a full device step")

    def check(self, ctx):
        if not ctx.config.in_lock_scope(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    # ------------------------------------------------------------------
    def _check_class(self, ctx, cls):
        info = self._scan(ctx, cls)
        if not info.locks:
            return
        shared = set(info.locked_writes)
        for attr, node, method in info.bare_writes:
            if attr in shared:
                yield self.finding(
                    ctx, node,
                    f"`self.{attr}` is written under the lock in "
                    f"`{self._owner(ctx, info, attr)}` but bare in "
                    f"`{method.name}`: racing the locked readers — "
                    "take the lock or document single-thread ownership")
        for node, method, lock_attr in info.locked_calls:
            yield self.finding(
                ctx, node,
                f"engine/device call while holding `self.{lock_attr}` "
                f"in `{method.name}`: every other thread blocks for "
                "the whole device step — move the call outside the "
                "lock and publish results after")

    def _owner(self, ctx, info, attr):
        node = info.locked_writes[attr]
        fn = ctx.enclosing_function(node)
        return fn.name if fn is not None else "<module>"

    # ------------------------------------------------------------------
    def _scan(self, ctx, cls):
        info = _ClassInfo(cls)
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # pass 1: which attrs hold locks
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    leaf = dotted_name(node.value.func).rsplit(".", 1)[-1]
                    if leaf in _LOCK_TYPES:
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr:
                                info.locks.add(attr)
        if not info.locks:
            return info
        # pass 2: classify writes + calls by locked-ness
        for m in methods:
            is_init = m.name == "__init__"
            locked_by_name = m.name.endswith("_locked")
            for node in ast.walk(m):
                lock_attr = self._held_lock(ctx, node, info.locks, m)
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None or attr in info.locks:
                            continue
                        if lock_attr or locked_by_name:
                            info.locked_writes.setdefault(attr, node)
                        elif not is_init:
                            info.bare_writes.append((attr, node, m))
                elif isinstance(node, ast.Call) and lock_attr:
                    if self._is_blocking_call(ctx, node):
                        info.locked_calls.append((node, m, lock_attr))
        return info

    def _held_lock(self, ctx, node, locks, method):
        """Name of the lock attr whose `with self.<lock>:` encloses
        `node` (searching only within `method`)."""
        for p in ctx.parents(node):
            if p is method:
                break
            if isinstance(p, ast.With):
                for item in p.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                        # with self._cond.acquire_timeout(...) style
                        if isinstance(expr, ast.Attribute):
                            expr = expr.value
                    attr = _self_attr(expr)
                    if attr in locks:
                        return attr
        return None

    def _is_blocking_call(self, ctx, call):
        name = dotted_name(call.func)
        if not name:
            return False
        leaf = name.rsplit(".", 1)[-1]
        if leaf not in _BLOCKING_LEAFS:
            return False
        # `self._cond.wait(timeout=...)` etc. are how condition vars
        # are used; don't confuse them with blocking device work.
        return True
