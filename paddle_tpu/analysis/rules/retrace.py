"""TPL002 — retrace hazards in jitted code.

jit caches one executable per (shapes, dtypes, static-arg values)
signature. Python control flow on traced values either crashes
(TracerBoolConversionError) or — when keyed off `.shape`/`len()` —
silently compiles a fresh executable per distinct shape: the retrace
storm that turns a serving warm-up into minutes of XLA time.
"""
from __future__ import annotations

import ast

from ..context import dotted_name
from ..engine import Rule, Severity, register

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


@register
class RetraceRule(Rule):
    id = "TPL002"
    name = "retrace-hazard"
    severity = Severity.WARNING
    rationale = ("Python control flow on traced values/shapes inside "
                 "jit compiles one executable per distinct signature")

    def check(self, ctx):
        for fn in ctx.traced_functions:
            params = ctx.function_params(fn)
            yield from self._check_control_flow(ctx, fn, params)
            yield from self._check_format_deps(ctx, fn, params)
        yield from self._check_static_args(ctx)

    # -- Python control flow over traced/shape values -------------------
    def _check_control_flow(self, ctx, fn, params):
        for node in ast.walk(fn):
            # nested defs are traced too and visited on their own pass
            if ctx.enclosing_function(node) is not fn:
                continue
            if isinstance(node, (ast.If, ast.While)):
                yield from self._flag_test(ctx, node.test, params,
                                           kind=type(node).__name__.lower())
            elif isinstance(node, ast.IfExp):
                yield from self._flag_test(ctx, node.test, params,
                                           kind="conditional expression")
            elif isinstance(node, ast.For):
                yield from self._flag_loop(ctx, node, params)

    def _flag_test(self, ctx, test, params, kind):
        # `x is None` / isinstance() / flag-style names are static
        # Python: branching on them is how jit code selects variants.
        if self._is_static_test(ctx, test, params):
            return
        if ctx.expr_mentions_shape(test):
            yield self.finding(
                ctx, test,
                f"`{kind}` on a shape-dependent value in a jitted body: "
                "one retrace per distinct shape — pad to a bucket or "
                "use lax.cond/jnp.where")
        elif ctx.expr_mentions_param(test, params):
            yield self.finding(
                ctx, test,
                f"`{kind}` on a possibly-traced value in a jitted body: "
                "crashes under trace or silently retraces — use "
                "lax.cond/jnp.where, or mark the argument static")

    def _flag_loop(self, ctx, node, params):
        it = node.iter
        # for i in range(x.shape[0]) — unrolled shape-dependent loop
        if isinstance(it, ast.Call) and \
                dotted_name(it.func) in ("range", "reversed"):
            for arg in it.args:
                if ctx.expr_mentions_shape(arg):
                    yield self.finding(
                        ctx, node,
                        "`for` over a shape-dependent range in a jitted "
                        "body: unrolls into the HLO and retraces per "
                        "shape — use lax.fori_loop/lax.scan")
                    return
        elif ctx.expr_mentions_param(it, params) and \
                not ctx.expr_mentions_shape(it):
            yield self.finding(
                ctx, node,
                "`for` directly over a traced value in a jitted body: "
                "unrolls (or crashes) under trace — use lax.scan")

    def _is_static_test(self, ctx, test, params):
        if isinstance(test, ast.Compare) and \
                any(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            return True
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                leaf = dotted_name(sub.func).rsplit(".", 1)[-1]
                if leaf in ("isinstance", "hasattr", "callable",
                            "issubclass"):
                    return True
        return False

    # -- shape/tracer leakage through f-strings and dict keys -----------
    def _check_format_deps(self, ctx, fn, params):
        for node in ast.walk(fn):
            if ctx.enclosing_function(node) is not fn:
                continue
            if isinstance(node, ast.JoinedStr):
                for val in node.values:
                    if isinstance(val, ast.FormattedValue) and \
                            (ctx.expr_mentions_shape(val.value) or
                             ctx.expr_mentions_param(val.value, params)):
                        yield self.finding(
                            ctx, node,
                            "f-string over a traced/shape value in a "
                            "jitted body: formatting concretizes — move "
                            "logging out of the traced region")
                        break
            elif isinstance(node, ast.Subscript) and \
                    ctx.expr_mentions_shape(node.slice):
                parent = getattr(node, "_tpul_parent", None)
                if isinstance(parent, (ast.Assign, ast.AugAssign)) or \
                        isinstance(node.slice, (ast.Tuple, ast.Attribute)):
                    yield self.finding(
                        ctx, node,
                        "shape-keyed lookup in a jitted body: the key "
                        "changes per input shape, so the trace is "
                        "shape-dependent — hoist it to the caller")

    # -- non-hashable static args ---------------------------------------
    def _check_static_args(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static_names = set()
            for dec in node.decorator_list:
                static_names |= self._static_names_of(ctx, dec, node)
            if not static_names:
                continue
            a = node.args
            pos = a.posonlyargs + a.args
            defaults = dict(zip([p.arg for p in pos[len(pos)
                                                   - len(a.defaults):]],
                                a.defaults))
            defaults.update({p.arg: d for p, d in
                             zip(a.kwonlyargs, a.kw_defaults)
                             if d is not None})
            for name in sorted(static_names):
                d = defaults.get(name)
                if d is not None and isinstance(d, _MUTABLE_LITERALS):
                    yield self.finding(
                        ctx, d,
                        f"static arg `{name}` of jitted `{node.name}` "
                        "defaults to a non-hashable value: every call "
                        "misses the jit cache (unhashable) or keys on "
                        "identity — use a tuple/frozen config")

    def _static_names_of(self, ctx, dec, fn):
        """Names listed in static_argnames=/static_argnums= of a jit
        decorator (possibly spelled via functools.partial)."""
        if not isinstance(dec, ast.Call):
            return set()
        names = set()
        a = fn.args
        pos = [p.arg for p in a.posonlyargs + a.args]
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        names.add(sub.value)
            elif kw.arg == "static_argnums":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, int) and \
                            0 <= sub.value < len(pos):
                        names.add(pos[sub.value])
        return names
