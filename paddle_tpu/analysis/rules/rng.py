"""TPL003 — untraced randomness inside traced code.

`np.random.*` / `random.*` execute once at TRACE time: the sampled
value is baked into the compiled executable as a constant, so every
subsequent call replays the same "random" numbers, and different
hosts trace different constants — silent determinism and parity
breakage. Traced code must thread `jax.random` keys.
"""
from __future__ import annotations

import ast

from ..engine import Rule, Severity, register

_HOST_RNG_ROOTS = ("numpy.random", "random")


@register
class UntracedRandomRule(Rule):
    id = "TPL003"
    name = "untraced-randomness"
    severity = Severity.ERROR
    rationale = ("host RNG inside a traced body is baked in as a "
                 "trace-time constant — non-deterministic across "
                 "hosts, constant across calls")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.in_traced_code(node) is None:
                continue
            target = ctx.resolve(node.func)
            if not target:
                continue
            if target.startswith("numpy.random.") or \
                    target == "numpy.random":
                yield self.finding(
                    ctx, node,
                    f"`{target}` inside a jitted body runs at trace "
                    "time: the value is a compiled-in constant — "
                    "thread a jax.random key instead")
            elif target.startswith("random.") and \
                    ctx.import_aliases.get("random") == "random":
                yield self.finding(
                    ctx, node,
                    f"stdlib `{target}` inside a jitted body runs at "
                    "trace time: the value is a compiled-in constant "
                    "— thread a jax.random key instead")
