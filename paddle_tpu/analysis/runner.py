"""Drive the rules over files/trees and fold in suppressions."""
from __future__ import annotations

import os

from .config import DEFAULT_CONFIG
from .context import FileContext
from .engine import (Finding, Severity, all_rules, apply_suppressions,
                     Suppressions)


def lint_source(source, path="<string>", config=None, rules=None):
    """Lint one source string. Returns all findings, with suppressed
    ones marked (filter on `f.suppressed` for the gate)."""
    config = config or DEFAULT_CONFIG
    try:
        ctx = FileContext(path, source, config)
    except SyntaxError as e:
        return [Finding(rule="TPL000", severity=Severity.ERROR, path=path,
                        line=e.lineno or 1, col=(e.offset or 1) - 1,
                        message=f"syntax error: {e.msg}")]
    selected = rules if rules is not None else all_rules()
    findings = []
    for rule in selected:
        findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return apply_suppressions(findings,
                              Suppressions.scan(ctx.lines))


def lint_file(path, config=None, rules=None):
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, config=config, rules=rules)


def iter_python_files(paths, config=None):
    config = config or DEFAULT_CONFIG
    for p in paths:
        if os.path.isfile(p):
            if not config.is_excluded(p):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                if not config.is_excluded(full):
                    yield full


def lint_paths(paths, config=None, rules=None):
    """Lint files/directories. Returns (findings, files_scanned)."""
    config = config or DEFAULT_CONFIG
    findings, nfiles = [], 0
    for path in iter_python_files(paths, config):
        nfiles += 1
        findings.extend(lint_file(path, config=config, rules=rules))
    return findings, nfiles
