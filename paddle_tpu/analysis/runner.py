"""Drive the rules over files/trees and fold in suppressions.

Two phases since the tpuracer pass: every file is parsed first and the
whole-program `ProjectIndex` (thread entries, lock graph, attribute
ownership, env/metric contracts) is built over all of them; only then
do the rules run per file, with `ctx.project` carrying the index so
the cross-file rules (TPL007–TPL011) can judge the full picture while
emitting each finding at its single witness line.

A path that does not exist, cannot be read, or fails to parse is a
hard TPL000 finding — never a silent skip — so the CI gate exits 1 the
moment its input list rots.
"""
from __future__ import annotations

import os

from .config import DEFAULT_CONFIG
from .context import FileContext
from .engine import (Finding, Severity, all_rules, apply_suppressions,
                     Suppressions)
from .project import ProjectIndex


def _hard_finding(path, message, line=1, col=0):
    return Finding(rule="TPL000", severity=Severity.ERROR, path=path,
                   line=line, col=col, message=message)


def _check_file(ctx, config, rules):
    findings = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return apply_suppressions(findings, Suppressions.scan(ctx.lines))


def lint_source(source, path="<string>", config=None, rules=None,
                project=None):
    """Lint one source string. Returns all findings, with suppressed
    ones marked (filter on `f.suppressed` for the gate). Cross-file
    rules see a single-file project index unless one is passed in."""
    config = config or DEFAULT_CONFIG
    try:
        ctx = FileContext(path, source, config)
    except SyntaxError as e:
        return [_hard_finding(path, f"syntax error: {e.msg}",
                              line=e.lineno or 1, col=(e.offset or 1) - 1)]
    ctx.project = project if project is not None \
        else ProjectIndex.build([ctx], config)
    return _check_file(ctx, config,
                       rules if rules is not None else all_rules())


def lint_file(path, config=None, rules=None):
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, config=config, rules=rules)


def iter_python_files(paths, config=None):
    config = config or DEFAULT_CONFIG
    for p in paths:
        if os.path.isfile(p):
            if not config.is_excluded(p):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                if not config.is_excluded(full):
                    yield full


def analyze_paths(paths, config=None, rules=None):
    """Full two-phase run. Returns (findings, files_scanned, project);
    the project index covers every parseable file, even when a rule
    subset was selected."""
    config = config or DEFAULT_CONFIG
    findings = []
    files = []
    for p in paths:
        if not os.path.exists(p):
            findings.append(_hard_finding(
                p, "path does not exist — fix the lint invocation "
                   "(a gate that silently skips inputs is no gate)"))
            continue
        files.extend(iter_python_files([p], config))
    contexts = {}
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(_hard_finding(
                path, f"cannot read file: {e}"))
            continue
        try:
            contexts[path] = FileContext(path, source, config)
        except SyntaxError as e:
            findings.append(_hard_finding(
                path, f"syntax error: {e.msg}",
                line=e.lineno or 1, col=(e.offset or 1) - 1))
    project = ProjectIndex.build(list(contexts.values()), config)
    selected = rules if rules is not None else all_rules()
    for path in sorted(contexts):
        ctx = contexts[path]
        ctx.project = project
        findings.extend(_check_file(ctx, config, selected))
    return findings, len(files), project


def lint_paths(paths, config=None, rules=None):
    """Lint files/directories. Returns (findings, files_scanned)."""
    findings, nfiles, _ = analyze_paths(paths, config=config,
                                        rules=rules)
    return findings, nfiles
