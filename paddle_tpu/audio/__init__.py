"""paddle.audio parity (reference: python/paddle/audio).

Feature extractors (spectrogram/mel/MFCC) over our fft ops — TPU-ready
jnp graphs. File I/O backends are gated (no soundfile in image).
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, apply, unwrap
from ..nn.layer.layers import Layer

from . import functional  # noqa: E402,F401


class features:
    class Spectrogram(Layer):
        def __init__(self, n_fft=512, hop_length=None, win_length=None,
                     window="hann", power=2.0, center=True, pad_mode="reflect",
                     dtype="float32"):
            super().__init__()
            self.n_fft = n_fft
            self.hop = hop_length or n_fft // 4
            self.win_length = win_length or n_fft
            self.power = power
            self.center = center
            self.pad_mode = pad_mode
            self.window = functional.get_window(window, self.win_length)

        def forward(self, x):
            return functional.spectrogram(x, self.n_fft, self.hop,
                                          self.window, self.power,
                                          self.center, self.pad_mode)

    class MelSpectrogram(Layer):
        def __init__(self, sr=22050, n_fft=512, hop_length=None, n_mels=64,
                     f_min=50.0, f_max=None, power=2.0, **kw):
            super().__init__()
            self.spec = features.Spectrogram(n_fft, hop_length, power=power)
            self.fbank = functional.compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max or sr / 2)

        def forward(self, x):
            s = self.spec(x)
            return apply(lambda sp, fb: jnp.einsum("...ft,mf->...mt", sp, fb),
                         s, Tensor(self.fbank), name="mel")

    class LogMelSpectrogram(MelSpectrogram):
        def forward(self, x):
            mel = super().forward(x)
            return apply(lambda m: 10.0 * jnp.log10(jnp.maximum(m, 1e-10)),
                         mel, name="log_mel")

    class MFCC(Layer):
        def __init__(self, sr=22050, n_mfcc=40, n_fft=512, n_mels=64, **kw):
            super().__init__()
            self.logmel = features.LogMelSpectrogram(sr, n_fft, n_mels=n_mels)
            self.n_mfcc = n_mfcc

        def forward(self, x):
            lm = self.logmel(x)
            return functional.dct_ii(lm, self.n_mfcc)


class backends:
    @staticmethod
    def list_available_backends():
        return []

    @staticmethod
    def get_current_backend():
        return None

    @staticmethod
    def set_backend(name):
        raise RuntimeError("no audio I/O backend in this image; "
                           "feed numpy waveforms directly")


from . import datasets  # noqa: E402,F401
from . import backends  # noqa: E402,F401
from .backends import info, save  # noqa: E402,F401


def load(path, **kw):
    """WAV via the stdlib wave backend; .npy waveforms kept for the
    earlier rounds' offline path."""
    if str(path).endswith(".npy"):
        return Tensor(jnp.asarray(np.load(path))), 16000
    return backends.load(path, **kw)

# rebind `features` from the legacy namespace class to the real module
import paddle_tpu.audio.features as _features_mod  # noqa: E402

features = _features_mod
