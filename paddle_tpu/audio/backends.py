"""Audio file IO (reference: python/paddle/audio/backends — wave_backend).

The reference's default backend is a pure-python WAV reader/writer; same
here via the stdlib `wave` module (16-bit PCM), no external deps.
"""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np

from .._core.tensor import Tensor, unwrap

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=8 * f.getsampwidth())


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    if channels_first:
        data = data.T
    import jax.numpy as jnp
    return Tensor(jnp.asarray(np.ascontiguousarray(data))), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    data = np.asarray(unwrap(src))
    if channels_first:
        data = data.T
    if data.ndim == 1:
        data = data[:, None]
    if bits_per_sample != 16:
        raise ValueError("wave backend writes 16-bit PCM only "
                         "(reference wave_backend parity)")
    if np.issubdtype(data.dtype, np.floating):
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(data).tobytes())


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name not in ("wave_backend",):
        raise NotImplementedError(
            f"only the stdlib wave backend exists offline; got "
            f"{backend_name}")
