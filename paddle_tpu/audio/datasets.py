"""Audio datasets (reference: python/paddle/audio/datasets — TESS/ESC50).

Offline build: local-file mode reads WAVs from a directory laid out like
the reference datasets; without files, a seeded synthetic waveform set
keeps pipelines runnable (mirrors the vision datasets' offline policy).
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset
from .backends import load as _load

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]


class AudioClassificationDataset(Dataset):
    def __init__(self, files=None, labels=None, feat_type="raw",
                 sample_rate=16000, duration=1.0, n_classes=8, n_items=64,
                 archive=None, **kwargs):
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        if files:
            self.files = list(files)
            self.labels = list(labels)
            self._synthetic = None
        else:
            rng = np.random.RandomState(0)
            n = int(sample_rate * duration)
            t = np.arange(n) / sample_rate
            waves, labs = [], []
            for i in range(n_items):
                lab = i % n_classes
                f0 = 120.0 * (lab + 1)
                w = np.sin(2 * np.pi * f0 * t) + \
                    0.1 * rng.randn(n)
                waves.append(w.astype(np.float32))
                labs.append(lab)
            self._synthetic = waves
            self.labels = labs
            self.files = [None] * n_items

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        if self._synthetic is not None:
            wave = self._synthetic[idx]
        else:
            t, _sr = _load(self.files[idx], channels_first=False)
            wave = np.asarray(t.numpy())[:, 0]
        return wave, self.labels[idx]


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set layout (reference audio/datasets/
    tess.py): <root>/<speaker>_<word>_<emotion>.wav."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode="train", feat_type="raw", data_dir=None, **kw):
        if data_dir and os.path.isdir(data_dir):
            files, labels = [], []
            for fn in sorted(os.listdir(data_dir)):
                if fn.lower().endswith(".wav"):
                    emo = fn.rsplit("_", 1)[-1][:-4].lower()
                    if emo in self.EMOTIONS:
                        files.append(os.path.join(data_dir, fn))
                        labels.append(self.EMOTIONS.index(emo))
            super().__init__(files=files, labels=labels,
                             feat_type=feat_type, **kw)
        else:
            super().__init__(feat_type=feat_type,
                             n_classes=len(self.EMOTIONS), **kw)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds layout (reference audio/datasets/
    esc50.py): <root>/<fold>-<id>-<take>-<target>.wav."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, **kw):
        if data_dir and os.path.isdir(data_dir):
            files, labels = [], []
            for fn in sorted(os.listdir(data_dir)):
                if fn.endswith(".wav") and fn.count("-") >= 3:
                    fold = int(fn.split("-")[0])
                    target = int(fn[:-4].split("-")[-1])
                    train = fold != split
                    if (mode == "train") == train:
                        files.append(os.path.join(data_dir, fn))
                        labels.append(target)
            super().__init__(files=files, labels=labels,
                             feat_type=feat_type, **kw)
        else:
            super().__init__(feat_type=feat_type, n_classes=50, **kw)
