"""paddle.audio.features as a real module (reference: python/paddle/
audio/features/layers.py). The Layer classes were defined on a nested
namespace class in earlier rounds; lift them here and keep both access
styles working (the parent rebinds `features` to this module)."""
from __future__ import annotations

import sys as _sys

_cls = getattr(_sys.modules[__package__], "features")
Spectrogram = _cls.Spectrogram
MelSpectrogram = _cls.MelSpectrogram
LogMelSpectrogram = _cls.LogMelSpectrogram
MFCC = _cls.MFCC

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
