"""audio.functional (reference: python/paddle/audio/functional)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, apply, unwrap


def get_window(window, win_length, fftbins=True, dtype="float32"):
    n = np.arange(win_length)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / win_length)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / win_length)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / win_length) +
             0.08 * np.cos(4 * np.pi * n / win_length))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unknown window {window}")
    return jnp.asarray(w.astype(np.float32))


def hz_to_mel(f, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)
    f = np.asarray(f, np.float64)
    f_sp = 200.0 / 3
    mels = f / f_sp
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = np.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) /
                    logstep, mels)


def mel_to_hz(m, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)
    m = np.asarray(m, np.float64)
    f_sp = 200.0 / 3
    freqs = m * f_sp
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = np.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=50.0, f_max=None,
                         htk=False, norm="slaney"):
    f_max = f_max or sr / 2
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fbank = np.zeros((n_mels, n_bins), np.float32)
    for m in range(n_mels):
        lo, c, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(c - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - c, 1e-10)
        fbank[m] = np.maximum(0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fbank *= enorm[:, None]
    return jnp.asarray(fbank)


def spectrogram(x, n_fft, hop_length, window, power=2.0, center=True,
                pad_mode="reflect"):
    win = unwrap(window)

    def fn(a):
        wav = a
        if center:
            pad = n_fft // 2
            wav = jnp.pad(wav, [(0, 0)] * (wav.ndim - 1) + [(pad, pad)],
                          mode="reflect" if pad_mode == "reflect" else
                          "constant")
        n_frames = 1 + (wav.shape[-1] - n_fft) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length +
               jnp.arange(n_fft)[None, :])
        frames = wav[..., idx] * win
        spec = jnp.fft.rfft(frames, axis=-1)
        mag = jnp.abs(spec) ** power
        return jnp.swapaxes(mag, -1, -2)  # (..., freq, time)
    return apply(fn, x, name="spectrogram")


def dct_ii(x, n_out):
    def fn(a):
        n_in = a.shape[-2]
        k = np.arange(n_out)[:, None]
        n = np.arange(n_in)[None, :]
        basis = np.sqrt(2.0 / n_in) * np.cos(np.pi / n_in * (n + 0.5) * k)
        basis[0] /= np.sqrt(2.0)
        return jnp.einsum("...ft,kf->...kt", a, jnp.asarray(
            basis.astype(np.float32)))
    return apply(fn, x, name="dct")


def create_dct(n_mfcc, n_mels, norm="ortho"):
    k = np.arange(n_mfcc)[:, None]
    n = np.arange(n_mels)[None, :]
    basis = np.sqrt(2.0 / n_mels) * np.cos(np.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        basis[0] /= np.sqrt(2.0)
    return Tensor(jnp.asarray(basis.T.astype(np.float32)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """reference: audio/functional/functional.py fft_frequencies."""
    import jax.numpy as jnp
    return Tensor(jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2)
                  .astype(dtype))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """reference: mel_frequencies — n_mels points evenly spaced on the
    mel scale between f_min and f_max, back in Hz."""
    import jax.numpy as jnp
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(jnp.asarray(mel_to_hz(mels, htk)).astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0, name=None):
    """reference: power_to_db — 10*log10(S/ref) clipped to top_db."""
    import jax.numpy as jnp
    from .._core.tensor import apply as _apply

    def fn(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return _apply(fn, spect, name="power_to_db")
