"""Autograd public API (reference: python/paddle/autograd/__init__.py).

Eager tape + functional transforms. PyLayer maps onto jax.custom_vjp so
custom gradients survive jit/pjit tracing too — stronger than the
reference's dygraph-only PyLayer.
"""
from __future__ import annotations

import jax

from .._core.state import no_grad_ctx, enable_grad_ctx, set_grad_enabled, grad_enabled
from .._core.engine import grad, backward as _backward_one
from .._core.tensor import Tensor, apply, unwrap


class no_grad:
    """Context manager AND decorator, like paddle.no_grad."""

    def __enter__(self):
        self._ctx = no_grad_ctx()
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad_ctx():
                return fn(*a, **k)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._ctx = enable_grad_ctx()
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with enable_grad_ctx():
                return fn(*a, **k)
        return wrapper


class set_grad_enabled_ctx:
    def __init__(self, mode):
        self.mode = mode

    def __enter__(self):
        from .._core import state
        self.prev = state._state.grad_enabled
        state._state.grad_enabled = bool(self.mode)

    def __exit__(self, *exc):
        from .._core import state
        state._state.grad_enabled = self.prev


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward over a list of tensors."""
    ts = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    gs = grad_tensors if isinstance(grad_tensors, (list, tuple)) else \
        [grad_tensors] * len(ts)
    import jax.numpy as jnp
    from .._core.engine import _run_backward
    seeds = [jnp.ones_like(t._value) if g is None else unwrap(g)
             for t, g in zip(ts, gs)]
    _run_backward(list(ts), seeds, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    """Custom forward/backward (reference: python/paddle/autograd/py_layer.py).

    Subclass with @staticmethod forward(ctx, *args) and backward(ctx, *grads).
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]

        def fwd_pure(*raws):
            rebuilt = []
            it = iter(raws)
            for a in args:
                rebuilt.append(Tensor(next(it)) if isinstance(a, Tensor) else a)
            with no_grad_ctx():
                out = cls.forward(ctx, *rebuilt, **kwargs)
            multi = isinstance(out, (tuple, list))
            outs = tuple(unwrap(o) for o in out) if multi else unwrap(out)
            return outs

        raws = tuple(unwrap(t) for t in tensor_args)

        # closure implementing custom vjp via the user's backward
        def op(*raw_inputs):
            return fwd_pure(*raw_inputs)

        import jax.numpy as jnp

        def op_fwd(*raw_inputs):
            out = fwd_pure(*raw_inputs)
            return out, None

        def op_bwd(_, cts):
            with no_grad_ctx():
                if isinstance(cts, tuple):
                    gin = cls.backward(ctx, *[Tensor(c) for c in cts])
                else:
                    gin = cls.backward(ctx, Tensor(cts))
            if not isinstance(gin, (tuple, list)):
                gin = (gin,)
            return tuple(unwrap(g) if g is not None else jnp.zeros_like(r)
                         for g, r in zip(gin, raws))

        f = jax.custom_vjp(op)
        f.defvjp(op_fwd, op_bwd)
        return apply(f, *tensor_args, name=cls.__name__)


def custom_vjp(fn, fwd=None, bwd=None):
    return jax.custom_vjp(fn)


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian parity (dense)."""
    from ..tensor import stack
    ys_list = ys if isinstance(ys, (list, tuple)) else [ys]
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    import jax.numpy as jnp
    rows = []
    for y in ys_list:
        flat = y._value.reshape(-1)
        for i in range(flat.shape[0]):
            seed = jnp.zeros_like(flat).at[i].set(1.0).reshape(y._value.shape)
            gs = grad([y], xs_list, grad_outputs=[Tensor(seed)], retain_graph=True)
            rows.append([g._value.reshape(-1) for g in gs])
    jac = [jnp.stack([r[j] for r in rows]) for j in range(len(xs_list))]
    out = [Tensor(j) for j in jac]
    return out[0] if len(out) == 1 else out


def hessian(func_out, xs, batch_axis=None):
    raise NotImplementedError(
        "use paddle_tpu.functional.hessian (jax.hessian) on the functional path")


__all__ = ["no_grad", "enable_grad", "backward", "grad", "PyLayer",
           "PyLayerContext", "jacobian", "set_grad_enabled",
           "saved_tensors_hooks"]


class saved_tensors_hooks:
    """reference: paddle.autograd.saved_tensors_hooks — pack/unpack hooks
    over tensors the tape saves for backward. While the context is
    active, every recorded TapeNode stores pack_hook(raw) in place of
    each tensor-valued raw input and calls unpack_hook when its VJP runs
    — use it to compress, quantize, or checksum saved activations.

    NOTE on device-memory offload: packing transforms the tape's saved
    copy, but the live `Tensor` objects flowing through your model still
    hold their device arrays (they ARE the forward values), so a
    to-host pack hook alone does not shrink HBM. For memory-bound
    training use the compiled path with `jax.checkpoint` (llama_spmd
    remat / Trainer), which is the TPU-native answer to activation
    memory.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from .._core import tensor as _t
        _t._saved_tensor_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from .._core import tensor as _t
        _t._saved_tensor_hooks.pop()
        return False
