"""hapi callbacks (reference: python/paddle/callbacks.py → hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                              f"{k}: {v}" for k, v in (logs or {}).items())
            total = self.steps if self.steps else "?"
            print(f"Epoch {self.epoch}: step {step}/{total} - {items}", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                              f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done ({dur:.1f}s) - {items}", flush=True)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.mean(cur))
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if hasattr(self.model, "stop_training"):
                    self.model.stop_training = True


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoints"

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0 and self.model is not None:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)


class VisualDL(Callback):
    """VisualDL-parity metrics logging via utils.summary.LogWriter
    (JSONL event stream; the visualdl wheel is not in the TPU image)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _w(self):
        if self._writer is None:
            from ..utils.summary import LogWriter
            self._writer = LogWriter(logdir=self.log_dir)
        return self._writer

    def on_train_batch_end(self, step, logs=None):
        self._step = step
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"train/{k}", float(v), step)
            except (TypeError, ValueError):
                pass

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"epoch/{k}", float(v), epoch)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            # a reused callback instance must reopen a fresh event stream
            self._writer = None


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.wait = 0
        self.best = None
        self.mode = "min" if mode == "auto" and "acc" not in monitor else \
            ("max" if mode == "auto" else mode)
        self.min_lr = min_lr

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.mean(cur))
        better = self.best is None or \
            (cur < self.best if self.mode == "min" else cur > self.best)
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    try:
                        opt.set_lr(max(opt.get_lr() * self.factor, self.min_lr))
                    except RuntimeError:
                        pass
                self.wait = 0


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, verbose=2, metrics=None, mode="train"):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs) and verbose:
        cbs.append(ProgBarLogger(verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbs):
        cbs.append(LRScheduler())
    params = {"batch_size": batch_size, "epochs": epochs, "steps": steps,
              "verbose": verbose, "metrics": metrics or []}
    for c in cbs:
        c.set_params(params)
        c.set_model(model)
    return CallbackList(cbs)


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class WandbCallback(Callback):
    """reference: callbacks/callbacks.py WandbCallback — logs metrics to
    Weights & Biases. wandb is not in this offline image; the callback
    degrades to a no-op with a one-time notice (same metrics flow through
    VisualDL / history)."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        self._cfg = dict(project=project, entity=entity, name=name,
                         dir=dir, mode=mode, job_type=job_type, **kwargs)
        self._run = None
        self._warned = False

    def _wandb(self):
        try:
            import wandb
            return wandb
        except ImportError:
            if not self._warned:
                print("[WandbCallback] wandb not installed; metrics are "
                      "not forwarded (offline build)")
                self._warned = True
            return None

    def on_train_begin(self, logs=None):
        w = self._wandb()
        if w is not None and self._run is None:
            self._run = w.init(**{k: v for k, v in self._cfg.items()
                                  if v is not None})

    def on_epoch_end(self, epoch, logs=None):
        if self._run is not None:
            self._run.log(dict(logs or {}, epoch=epoch))

    def on_train_end(self, logs=None):
        if self._run is not None:
            self._run.finish()
            self._run = None
