// libptckpt: packed-checkpoint writer/reader.
//
// Replaces the reference's C++ checkpoint serialization (fluid
// save/load_combine ops): many tensors packed into ONE file with an
// index footer, written by a background thread so the trainer overlaps
// device→host transfers of the next tensor with disk writes of the
// previous one. Commit is atomic: write to <path>.tmp, fsync, rename.
//
// Layout: [u64 magic][blob bytes ...][index][u64 index_off][u64 magic]
// index: u64 n, then per entry { u32 name_len, name bytes,
//                                u64 offset, u64 nbytes }.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x70746b7074636b31ULL;  // "ptkptck1"

struct Entry {
  std::string name;
  uint64_t offset;
  uint64_t nbytes;
};

struct Chunk {
  std::string name;
  std::vector<uint8_t> data;
};

struct Writer {
  std::string final_path, tmp_path;
  FILE* f = nullptr;
  uint64_t cursor = 0;
  std::vector<Entry> index;
  // background write queue
  std::queue<Chunk> q;
  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;
  bool closing = false;
  bool error = false;

  void run() {
    for (;;) {
      Chunk c;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return closing || !q.empty(); });
        if (q.empty()) {
          if (closing) return;
          continue;
        }
        c = std::move(q.front());
        q.pop();
      }
      cv.notify_all();
      if (!error) {
        index.push_back(Entry{c.name, cursor, c.data.size()});
        if (fwrite(c.data.data(), 1, c.data.size(), f) != c.data.size())
          error = true;
        cursor += c.data.size();
      }
    }
  }
};

struct Reader {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t len = 0;
  std::vector<Entry> index;
};

void put_u64(FILE* f, uint64_t v) { fwrite(&v, 8, 1, f); }

}  // namespace

extern "C" {

void* ptckpt_writer_open(const char* path) {
  auto* w = new Writer();
  w->final_path = path;
  w->tmp_path = w->final_path + ".tmp";
  w->f = fopen(w->tmp_path.c_str(), "wb");
  if (!w->f) { delete w; return nullptr; }
  put_u64(w->f, kMagic);
  w->cursor = 8;
  w->worker = std::thread([w] { w->run(); });
  return w;
}

// Enqueue one tensor blob; copies the buffer (caller may reuse it).
int ptckpt_write(void* h, const char* name, const uint8_t* data,
                 int64_t nbytes) {
  auto* w = static_cast<Writer*>(h);
  if (w->error) return -1;
  Chunk c;
  c.name = name;
  c.data.assign(data, data + nbytes);
  {
    std::unique_lock<std::mutex> lk(w->mu);
    // bound queue memory: at most 4 chunks in flight
    w->cv.wait(lk, [&] { return w->q.size() < 4; });
    w->q.push(std::move(c));
  }
  w->cv.notify_all();
  return 0;
}

// Flush queue, write index, fsync, atomic rename. Returns 0 on success.
int ptckpt_writer_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->closing = true;
  }
  w->cv.notify_all();
  w->worker.join();
  int rc = -1;
  if (!w->error) {
    uint64_t index_off = w->cursor;
    uint64_t n = w->index.size();
    fwrite(&n, 8, 1, w->f);
    for (const Entry& e : w->index) {
      uint32_t nl = uint32_t(e.name.size());
      fwrite(&nl, 4, 1, w->f);
      fwrite(e.name.data(), 1, nl, w->f);
      fwrite(&e.offset, 8, 1, w->f);
      fwrite(&e.nbytes, 8, 1, w->f);
    }
    put_u64(w->f, index_off);
    put_u64(w->f, kMagic);
    fflush(w->f);
    fsync(fileno(w->f));
    fclose(w->f);
    rc = rename(w->tmp_path.c_str(), w->final_path.c_str());
  } else {
    fclose(w->f);
    remove(w->tmp_path.c_str());
  }
  delete w;
  return rc;
}

void* ptckpt_reader_open(const char* path) {
  auto* r = new Reader();
  r->fd = open(path, O_RDONLY);
  if (r->fd < 0) { delete r; return nullptr; }
  struct stat st;
  fstat(r->fd, &st);
  r->len = size_t(st.st_size);
  r->map = static_cast<uint8_t*>(
      mmap(nullptr, r->len, PROT_READ, MAP_PRIVATE, r->fd, 0));
  if (r->map == MAP_FAILED) {
    close(r->fd); delete r; return nullptr;
  }
  if (r->len < 24) {
    munmap(r->map, r->len); close(r->fd); delete r; return nullptr;
  }
  uint64_t magic_head, magic_tail, index_off;
  memcpy(&magic_head, r->map, 8);
  memcpy(&magic_tail, r->map + r->len - 8, 8);
  memcpy(&index_off, r->map + r->len - 16, 8);
  // the index must live between the header magic and the footer;
  // compare without adding to index_off (a crafted value near 2^64
  // would wrap and defeat the check)
  if (magic_head != kMagic || magic_tail != kMagic ||
      index_off < 8 || index_off > r->len - 24) {
    munmap(r->map, r->len); close(r->fd); delete r; return nullptr;
  }
  // Bounds-check every index entry against the mapped range: a truncated
  // or corrupt file with intact magics must fail to open, not read OOB.
  const uint8_t* p = r->map + index_off;
  const uint8_t* end = r->map + r->len - 16;  // index stops at the footer
  uint64_t n;
  memcpy(&n, p, 8); p += 8;
  for (uint64_t i = 0; i < n; ++i) {
    uint32_t nl;
    if (p + 4 > end) goto corrupt;
    memcpy(&nl, p, 4); p += 4;
    if (nl > size_t(end - p) || size_t(end - p) < nl + 16) goto corrupt;
    {
      Entry e;
      e.name.assign(reinterpret_cast<const char*>(p), nl); p += nl;
      memcpy(&e.offset, p, 8); p += 8;
      memcpy(&e.nbytes, p, 8); p += 8;
      // blob must sit entirely in [8, index_off)
      if (e.offset < 8 || e.offset > index_off ||
          e.nbytes > index_off - e.offset) goto corrupt;
      r->index.push_back(std::move(e));
    }
  }
  return r;
corrupt:
  munmap(r->map, r->len); close(r->fd); delete r; return nullptr;
}

int64_t ptckpt_num_entries(void* h) {
  return int64_t(static_cast<Reader*>(h)->index.size());
}

// Copies entry i's name into buf (cap bytes incl. NUL); returns name len.
int64_t ptckpt_entry_name(void* h, int64_t i, char* buf, int64_t cap) {
  auto& e = static_cast<Reader*>(h)->index[size_t(i)];
  int64_t n = int64_t(e.name.size());
  if (n + 1 > cap) return -1;
  memcpy(buf, e.name.data(), n);
  buf[n] = 0;
  return n;
}

int64_t ptckpt_entry_size(void* h, const char* name) {
  auto* r = static_cast<Reader*>(h);
  for (auto& e : r->index)
    if (e.name == name) return int64_t(e.nbytes);
  return -1;
}

int64_t ptckpt_read(void* h, const char* name, uint8_t* out, int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  for (auto& e : r->index) {
    if (e.name == name) {
      if (int64_t(e.nbytes) > cap) return -2;
      memcpy(out, r->map + e.offset, e.nbytes);
      return int64_t(e.nbytes);
    }
  }
  return -1;
}

void ptckpt_reader_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  munmap(r->map, r->len);
  close(r->fd);
  delete r;
}

}  // extern "C"
