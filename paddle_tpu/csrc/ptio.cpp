// libptio — native data-pipeline core for paddle_tpu.
//
// Replaces the reference's C++ DataLoader machinery
// (paddle/fluid/operators/reader/blocking_queue.h + buffered_reader.cc):
// an mmap'd fixed-record reader, epoch shuffling (xoshiro PRNG), a
// multi-threaded batch-assembly pool, and a bounded prefetch queue the
// Python DataLoader drains via ctypes. Keeps TPU host CPUs feeding HBM
// without the GIL in the hot path.
//
// Build: make -C paddle_tpu/csrc  → libptio.so (ctypes, no pybind11).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ----------------------------------------------------------- PRNG
struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    uint64_t z = seed + 0x9e3779b97f4a7c15ull;
    for (auto& si : s) {
      z += 0x9e3779b97f4a7c15ull;
      uint64_t x = z;
      x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
      x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
      si = x ^ (x >> 31);
    }
  }
  static uint64_t rotl(uint64_t x, int k) { return (x << k) | (x >> (64 - k)); }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
};

// ----------------------------------------------------------- records
struct RecordFile {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t bytes = 0;
  size_t record_bytes = 0;
  size_t n_records = 0;
};

// ----------------------------------------------------------- pipeline
struct Batch {
  std::vector<uint8_t> buf;
  int64_t n = 0;      // samples in batch
  int64_t seq = 0;    // ordering key
};

struct Pipeline {
  RecordFile* rf = nullptr;
  int64_t batch_size = 0;
  bool shuffle = false;
  bool drop_last = true;
  uint64_t seed = 0;
  int64_t capacity = 4;

  std::vector<uint64_t> order;       // shuffled indices for the epoch
  std::atomic<int64_t> next_batch{0};
  int64_t n_batches = 0;

  std::deque<Batch> queue;           // completed batches (ordered pop)
  int64_t next_emit = 0;             // next seq to hand to python
  std::mutex mu;
  std::condition_variable cv_room;   // producers wait for queue room
  std::condition_variable cv_data;   // consumer waits for next_emit batch
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;

  ~Pipeline() { shutdown(); }

  void shutdown() {
    stop.store(true);
    cv_room.notify_all();
    cv_data.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
  }

  void start_epoch(uint64_t epoch, int n_threads) {
    shutdown();
    stop.store(false);
    size_t n = rf->n_records;
    order.resize(n);
    for (size_t i = 0; i < n; i++) order[i] = i;
    if (shuffle) {
      Xoshiro256 rng(seed * 2654435761ull + epoch + 1);
      for (size_t i = n - 1; i > 0; i--) {
        size_t j = rng.next() % (i + 1);
        std::swap(order[i], order[j]);
      }
    }
    n_batches = drop_last ? (int64_t)(n / batch_size)
                          : (int64_t)((n + batch_size - 1) / batch_size);
    next_batch.store(0);
    next_emit = 0;
    queue.clear();
    for (int t = 0; t < n_threads; t++)
      workers.emplace_back([this] { work(); });
  }

  void work() {
    const size_t rb = rf->record_bytes;
    while (!stop.load()) {
      int64_t b = next_batch.fetch_add(1);
      if (b >= n_batches) return;
      int64_t lo = b * batch_size;
      int64_t hi = std::min<int64_t>(lo + batch_size, (int64_t)order.size());
      Batch out;
      out.n = hi - lo;
      out.seq = b;
      out.buf.resize((size_t)(hi - lo) * rb);
      for (int64_t i = lo; i < hi; i++)
        std::memcpy(out.buf.data() + (size_t)(i - lo) * rb,
                    rf->data + order[(size_t)i] * rb, rb);
      std::unique_lock<std::mutex> lk(mu);
      cv_room.wait(lk, [this] {
        return stop.load() || (int64_t)queue.size() < capacity;
      });
      if (stop.load()) return;
      queue.push_back(std::move(out));
      cv_data.notify_all();
    }
  }

  // Returns samples copied (0 → epoch done), -1 on shutdown.
  int64_t next(uint8_t* dst) {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      if (next_emit >= n_batches) return 0;
      // find batch with seq == next_emit
      for (auto it = queue.begin(); it != queue.end(); ++it) {
        if (it->seq == next_emit) {
          std::memcpy(dst, it->buf.data(), it->buf.size());
          int64_t n = it->n;
          queue.erase(it);
          next_emit++;
          cv_room.notify_all();
          return n;
        }
      }
      if (stop.load()) return -1;
      cv_data.wait(lk);
    }
  }
};

}  // namespace

extern "C" {

void* ptio_open_records(const char* path, int64_t record_bytes) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (p == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(p, (size_t)st.st_size, MADV_WILLNEED);
  auto* rf = new RecordFile();
  rf->fd = fd;
  rf->data = static_cast<const uint8_t*>(p);
  rf->bytes = (size_t)st.st_size;
  rf->record_bytes = (size_t)record_bytes;
  rf->n_records = rf->bytes / rf->record_bytes;
  return rf;
}

int64_t ptio_num_records(void* handle) {
  return handle ? (int64_t)static_cast<RecordFile*>(handle)->n_records : -1;
}

void ptio_close_records(void* handle) {
  if (!handle) return;
  auto* rf = static_cast<RecordFile*>(handle);
  munmap(const_cast<uint8_t*>(rf->data), rf->bytes);
  ::close(rf->fd);
  delete rf;
}

void* ptio_pipeline_create(void* records, int64_t batch_size, int shuffle,
                           int drop_last, uint64_t seed, int64_t capacity) {
  if (!records) return nullptr;
  auto* p = new Pipeline();
  p->rf = static_cast<RecordFile*>(records);
  p->batch_size = batch_size;
  p->shuffle = shuffle != 0;
  p->drop_last = drop_last != 0;
  p->seed = seed;
  p->capacity = capacity > 0 ? capacity : 4;
  return p;
}

void ptio_pipeline_start_epoch(void* pipeline, uint64_t epoch, int n_threads) {
  if (!pipeline) return;
  static_cast<Pipeline*>(pipeline)->start_epoch(
      epoch, n_threads > 0 ? n_threads : 2);
}

int64_t ptio_pipeline_num_batches(void* pipeline) {
  return pipeline ? static_cast<Pipeline*>(pipeline)->n_batches : -1;
}

int64_t ptio_pipeline_next(void* pipeline, uint8_t* dst) {
  return pipeline ? static_cast<Pipeline*>(pipeline)->next(dst) : -1;
}

void ptio_pipeline_destroy(void* pipeline) {
  delete static_cast<Pipeline*>(pipeline);
}

// ----------------------------------------------------------- staging pool
// Page-aligned host staging buffers for H2D overlap (the reference keeps
// pinned CUDA buffers; XLA TPU wants aligned host memory for fast DMA).
void* ptio_alloc_staging(int64_t bytes) {
  void* p = nullptr;
  if (posix_memalign(&p, 4096, (size_t)bytes) != 0) return nullptr;
  return p;
}

void ptio_free_staging(void* p) { free(p); }

}  // extern "C"
