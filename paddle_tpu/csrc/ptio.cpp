// libptio — native data-pipeline core for paddle_tpu.
//
// Replaces the reference's C++ DataLoader machinery
// (paddle/fluid/operators/reader/blocking_queue.h + buffered_reader.cc):
// mmap'd record readers (fixed-size and varlen), epoch shuffling
// (xoshiro PRNG), a multi-threaded batch-assembly pool, and a bounded
// prefetch queue the Python DataLoader drains via ctypes. Keeps TPU host
// CPUs feeding HBM without the GIL in the hot path.
//
// Build: make -C paddle_tpu/csrc  → libptio.so (ctypes, no pybind11).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ----------------------------------------------------------- PRNG
struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    uint64_t z = seed + 0x9e3779b97f4a7c15ull;
    for (auto& si : s) {
      z += 0x9e3779b97f4a7c15ull;
      uint64_t x = z;
      x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
      x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
      si = x ^ (x >> 31);
    }
  }
  static uint64_t rotl(uint64_t x, int k) { return (x << k) | (x >> (64 - k)); }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
};

// ----------------------------------------------------------- records
struct RecordFile {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t bytes = 0;
  size_t record_bytes = 0;
  size_t n_records = 0;
};

// .ptvr layout: "PTVR" u32 version, u64 n, u64 offsets[n+1] (relative to
// the data region start), data blob. Offsets are validated against the
// mapped length on open — a truncated/corrupt file fails cleanly.
struct VarRecordFile {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t bytes = 0;
  const uint64_t* offsets = nullptr;  // n+1 entries
  const uint8_t* data = nullptr;
  size_t n_records = 0;
  size_t max_record = 0;
};

// ----------------------------------------------------------- pipeline core
struct Batch {
  std::vector<uint8_t> buf;
  std::vector<int64_t> sizes;  // per-record byte counts (varlen only)
  int64_t n = 0;               // samples in batch
  int64_t seq = 0;             // ordering key
};

// Shared threaded prefetch machinery: epoch shuffle, worker pool, bounded
// ordered-emit queue. Subclasses provide the record count and the
// per-batch copy. Concurrency invariants:
//   * stop.store happens under mu before notifying — a worker that has
//     evaluated its wait predicate but not yet slept would otherwise
//     miss the wakeup and the join would hang;
//   * a producer holding the NEXT in-order batch may exceed `capacity`,
//     otherwise out-of-order completions can fill the queue while the
//     consumer waits for exactly that batch — mutual deadlock.
struct PipelineCore {
  int64_t batch_size = 0;
  bool shuffle = false;
  bool drop_last = true;
  uint64_t seed = 0;
  int64_t capacity = 4;

  std::vector<uint64_t> order;  // shuffled indices for the epoch
  std::atomic<int64_t> next_batch{0};
  int64_t n_batches = 0;

  std::deque<Batch> queue;  // completed batches (ordered pop)
  int64_t next_emit = 0;    // next seq to hand to python (guarded by mu)
  std::mutex mu;
  std::condition_variable cv_room;  // producers wait for queue room
  std::condition_variable cv_data;  // consumer waits for next_emit batch
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;

  virtual ~PipelineCore() { shutdown(); }
  virtual size_t n_records() const = 0;
  virtual void assemble(int64_t lo, int64_t hi, Batch* out) = 0;

  void shutdown() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_room.notify_all();
    cv_data.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
  }

  int64_t batches_for(size_t n) const {
    if (batch_size <= 0) return 0;
    return drop_last ? (int64_t)(n / batch_size)
                     : (int64_t)((n + batch_size - 1) / batch_size);
  }

  void start_epoch(uint64_t epoch, int n_threads) {
    shutdown();
    stop.store(false);
    size_t n = n_records();
    order.resize(n);
    for (size_t i = 0; i < n; i++) order[i] = i;
    if (shuffle && n > 1) {
      Xoshiro256 rng(seed * 2654435761ull + epoch + 1);
      for (size_t i = n - 1; i > 0; i--) {
        size_t j = rng.next() % (i + 1);
        std::swap(order[i], order[j]);
      }
    }
    n_batches = batches_for(n);
    next_batch.store(0);
    next_emit = 0;
    queue.clear();
    for (int t = 0; t < n_threads; t++)
      workers.emplace_back([this] { work(); });
  }

  void work() {
    while (!stop.load()) {
      int64_t b = next_batch.fetch_add(1);
      if (b >= n_batches) return;
      int64_t lo = b * batch_size;
      int64_t hi = std::min<int64_t>(lo + batch_size, (int64_t)order.size());
      Batch out;
      out.n = hi - lo;
      out.seq = b;
      assemble(lo, hi, &out);
      std::unique_lock<std::mutex> lk(mu);
      cv_room.wait(lk, [this, &out] {
        return stop.load() || (int64_t)queue.size() < capacity ||
               out.seq == next_emit;  // in-order batch never blocks
      });
      if (stop.load()) return;
      queue.push_back(std::move(out));
      cv_data.notify_all();
    }
  }

  // dst: batch bytes; sizes: per-record byte counts (null for the
  // fixed-record path). Returns samples copied (0 → epoch done), -1 on
  // shutdown.
  int64_t next(uint8_t* dst, int64_t* sizes) {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      if (next_emit >= n_batches) return 0;
      for (auto it = queue.begin(); it != queue.end(); ++it) {
        if (it->seq == next_emit) {
          std::memcpy(dst, it->buf.data(), it->buf.size());
          if (sizes)
            for (size_t i = 0; i < it->sizes.size(); i++)
              sizes[i] = it->sizes[i];
          int64_t n = it->n;
          queue.erase(it);
          next_emit++;
          cv_room.notify_all();
          return n;
        }
      }
      if (stop.load()) return -1;
      cv_data.wait(lk);
    }
  }
};

struct FixedPipeline : PipelineCore {
  RecordFile* rf = nullptr;
  ~FixedPipeline() override { shutdown(); }
  size_t n_records() const override { return rf->n_records; }
  void assemble(int64_t lo, int64_t hi, Batch* out) override {
    const size_t rb = rf->record_bytes;
    out->buf.resize((size_t)(hi - lo) * rb);
    for (int64_t i = lo; i < hi; i++)
      std::memcpy(out->buf.data() + (size_t)(i - lo) * rb,
                  rf->data + order[(size_t)i] * rb, rb);
  }
};

struct VarPipeline : PipelineCore {
  VarRecordFile* rf = nullptr;
  ~VarPipeline() override { shutdown(); }
  size_t n_records() const override { return rf->n_records; }
  void assemble(int64_t lo, int64_t hi, Batch* out) override {
    out->sizes.reserve((size_t)(hi - lo));
    size_t total = 0;
    for (int64_t i = lo; i < hi; i++) {
      uint64_t r = order[(size_t)i];
      size_t sz = (size_t)(rf->offsets[r + 1] - rf->offsets[r]);
      out->sizes.push_back((int64_t)sz);
      total += sz;
    }
    out->buf.resize(total);
    size_t w = 0;
    for (int64_t i = lo; i < hi; i++) {
      uint64_t r = order[(size_t)i];
      size_t sz = (size_t)(rf->offsets[r + 1] - rf->offsets[r]);
      std::memcpy(out->buf.data() + w, rf->data + rf->offsets[r], sz);
      w += sz;
    }
  }
};

}  // namespace

extern "C" {

void* ptio_open_records(const char* path, int64_t record_bytes) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (p == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(p, (size_t)st.st_size, MADV_WILLNEED);
  auto* rf = new RecordFile();
  rf->fd = fd;
  rf->data = static_cast<const uint8_t*>(p);
  rf->bytes = (size_t)st.st_size;
  rf->record_bytes = (size_t)record_bytes;
  rf->n_records = rf->bytes / rf->record_bytes;
  return rf;
}

int64_t ptio_num_records(void* handle) {
  return handle ? (int64_t)static_cast<RecordFile*>(handle)->n_records : -1;
}

void ptio_close_records(void* handle) {
  if (!handle) return;
  auto* rf = static_cast<RecordFile*>(handle);
  munmap(const_cast<uint8_t*>(rf->data), rf->bytes);
  ::close(rf->fd);
  delete rf;
}

void* ptio_pipeline_create(void* records, int64_t batch_size, int shuffle,
                           int drop_last, uint64_t seed, int64_t capacity) {
  if (!records) return nullptr;
  auto* p = new FixedPipeline();
  p->rf = static_cast<RecordFile*>(records);
  p->batch_size = batch_size;
  p->shuffle = shuffle != 0;
  p->drop_last = drop_last != 0;
  p->seed = seed;
  p->capacity = capacity > 0 ? capacity : 4;
  return p;
}

void ptio_pipeline_start_epoch(void* pipeline, uint64_t epoch, int n_threads) {
  if (!pipeline) return;
  static_cast<FixedPipeline*>(pipeline)->start_epoch(
      epoch, n_threads > 0 ? n_threads : 2);
}

int64_t ptio_pipeline_num_batches(void* pipeline) {
  if (!pipeline) return -1;
  auto* p = static_cast<FixedPipeline*>(pipeline);
  return p->batches_for(p->n_records());
}

int64_t ptio_pipeline_next(void* pipeline, uint8_t* dst) {
  return pipeline ? static_cast<FixedPipeline*>(pipeline)->next(dst, nullptr)
                  : -1;
}

void ptio_pipeline_destroy(void* pipeline) {
  delete static_cast<FixedPipeline*>(pipeline);
}

// ----------------------------------------------------------- varlen API
void* ptio_open_varlen(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < 16) {
    ::close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (p == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* m = static_cast<const uint8_t*>(p);
  size_t len = (size_t)st.st_size;
  auto fail = [&]() -> void* {
    munmap(p, len);
    ::close(fd);
    return nullptr;
  };
  if (std::memcmp(m, "PTVR", 4) != 0) return fail();
  uint64_t n;
  std::memcpy(&n, m + 8, 8);
  // overflow-safe: the index alone needs (n+1)*8 bytes inside the file
  if (n >= (len - 16) / 8) return fail();
  size_t header = 16 + ((size_t)n + 1) * 8;
  if (len < header) return fail();
  const uint64_t* offs = reinterpret_cast<const uint64_t*>(m + 16);
  size_t data_len = len - header;
  // validate: monotone offsets ending inside the data region
  if (offs[0] != 0) return fail();
  for (uint64_t i = 0; i < n; i++)
    if (offs[i + 1] < offs[i] || offs[i + 1] > data_len) return fail();
  auto* rf = new VarRecordFile();
  rf->fd = fd;
  rf->map = m;
  rf->bytes = len;
  rf->offsets = offs;
  rf->data = m + header;
  rf->n_records = (size_t)n;
  size_t mx = 0;
  for (uint64_t i = 0; i < n; i++)
    mx = std::max(mx, (size_t)(offs[i + 1] - offs[i]));
  rf->max_record = mx;
  madvise(p, len, MADV_WILLNEED);
  return rf;
}

int64_t ptio_varlen_num_records(void* h) {
  return h ? (int64_t)static_cast<VarRecordFile*>(h)->n_records : -1;
}

int64_t ptio_varlen_max_record(void* h) {
  return h ? (int64_t)static_cast<VarRecordFile*>(h)->max_record : -1;
}

void ptio_close_varlen(void* h) {
  if (!h) return;
  auto* rf = static_cast<VarRecordFile*>(h);
  munmap(const_cast<uint8_t*>(rf->map), rf->bytes);
  ::close(rf->fd);
  delete rf;
}

void* ptio_varlen_pipeline_create(void* records, int64_t batch_size,
                                  int shuffle, int drop_last, uint64_t seed,
                                  int64_t capacity) {
  if (!records) return nullptr;
  auto* p = new VarPipeline();
  p->rf = static_cast<VarRecordFile*>(records);
  p->batch_size = batch_size;
  p->shuffle = shuffle != 0;
  p->drop_last = drop_last != 0;
  p->seed = seed;
  p->capacity = capacity > 0 ? capacity : 4;
  return p;
}

void ptio_varlen_pipeline_start_epoch(void* pl, uint64_t epoch,
                                      int n_threads) {
  if (!pl) return;
  static_cast<VarPipeline*>(pl)->start_epoch(epoch,
                                             n_threads > 0 ? n_threads : 2);
}

int64_t ptio_varlen_pipeline_num_batches(void* pl) {
  if (!pl) return -1;
  auto* p = static_cast<VarPipeline*>(pl);
  return p->batches_for(p->n_records());
}

int64_t ptio_varlen_pipeline_next(void* pl, uint8_t* dst, int64_t* sizes) {
  return pl ? static_cast<VarPipeline*>(pl)->next(dst, sizes) : -1;
}

void ptio_varlen_pipeline_destroy(void* pl) {
  delete static_cast<VarPipeline*>(pl);
}

// ----------------------------------------------------------- staging pool
// Page-aligned host staging buffers for H2D overlap (the reference keeps
// pinned CUDA buffers; XLA TPU wants aligned host memory for fast DMA).
void* ptio_alloc_staging(int64_t bytes) {
  void* p = nullptr;
  if (posix_memalign(&p, 4096, (size_t)bytes) != 0) return nullptr;
  return p;
}

void ptio_free_staging(void* p) { free(p); }

}  // extern "C"
