// libptps — native parameter-server shard (reference parity: the
// reference's PS tier is C++ BRPC services,
// paddle/fluid/distributed/ps/service/brpc_ps_server.cc; ours speaks
// the length-prefixed protocol of paddle_tpu/distributed/ps_impl.py so
// the Python PSClient/_RemoteShard works against either backend).
//
// One process-level table per server object: sparse rows keyed by
// int64 id, materialized on first pull with a deterministic
// splitmix64+Box-Muller init (deterministic per (seed, id), like the
// Python backend — the two backends' init STREAMS differ, which is
// fine: a table lives its whole life on one backend).
//
// Wire protocol (little-endian), one request/response per message:
//   header: u8 op | u16 table | u32 n_ids | u32 dim
//   u32 body_len
//   body:   n_ids * i64 ids, then f32 payload
// ops: 1=PULL (reply payload rows), 2=PUSH (ids+grads, reply empty),
//      3=LEN (reply one i64 id = row count), 4=STOP (reply empty,
//      shut the server down).
//
// Per-row optimizers match ps_impl.SparseTable: 0=sgd, 1=adagrad,
// 2=adam (per-row bias-correction step count).
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <climits>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t OP_PULL = 1, OP_PUSH = 2, OP_LEN = 3, OP_STOP = 4,
                  OP_SAVE = 5, OP_LOAD = 6;
constexpr uint32_t MAX_PATH_LEN = 4096;

uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

struct Table {
  int dim;
  int opt;  // 0 sgd, 1 adagrad, 2 adam
  float lr, init_scale, beta1, beta2, eps;
  int64_t seed;
  std::unordered_map<int64_t, size_t> slot;
  std::vector<float> rows, g2, m, v;
  std::vector<int64_t> steps;
  std::mutex mu;

  size_t ensure(int64_t id) {
    auto it = slot.find(id);
    if (it != slot.end()) return it->second;
    size_t s = slot.size();
    slot.emplace(id, s);
    size_t base = rows.size();
    rows.resize(base + dim);
    // deterministic init, two uniforms per normal. Mix the id through
    // splitmix64 FIRST: a plain linear key would make adjacent ids'
    // streams overlap (key(id+1) = key(id)+1), correlating neighboring
    // rows' inits — rec-sys ids are typically dense.
    uint64_t key = splitmix64(static_cast<uint64_t>(seed) ^
                              splitmix64(static_cast<uint64_t>(id)));
    for (int j = 0; j < dim; ++j) {
      uint64_t a = splitmix64(key + 2 * j + 1);
      uint64_t b = splitmix64(key + 2 * j + 2);
      double u1 = (static_cast<double>(a >> 11) + 1.0) / 9007199254740993.0;
      double u2 = static_cast<double>(b >> 11) / 9007199254740992.0;
      double n = std::sqrt(-2.0 * std::log(u1)) *
                 std::cos(2.0 * M_PI * u2);
      rows[base + j] = static_cast<float>(n * init_scale);
    }
    if (opt == 1) g2.resize(base + dim, 0.f);
    if (opt == 2) {
      m.resize(base + dim, 0.f);
      v.resize(base + dim, 0.f);
    }
    steps.resize(s + 1, 0);
    return s;
  }

  void pull(const int64_t* ids, uint32_t n, float* out) {
    std::lock_guard<std::mutex> g(mu);
    for (uint32_t i = 0; i < n; ++i) {
      size_t s = ensure(ids[i]);
      std::memcpy(out + static_cast<size_t>(i) * dim,
                  rows.data() + s * dim, sizeof(float) * dim);
    }
  }

  void push(const int64_t* ids, uint32_t n, const float* grads) {
    // scatter-add duplicates first (dense embedding backward
    // semantics), then apply the rule once per unique id
    std::unordered_map<int64_t, std::vector<float>> sum;
    sum.reserve(n);
    for (uint32_t i = 0; i < n; ++i) {
      auto& acc = sum[ids[i]];
      if (acc.empty()) acc.assign(dim, 0.f);
      const float* g = grads + static_cast<size_t>(i) * dim;
      for (int j = 0; j < dim; ++j) acc[j] += g[j];
    }
    std::lock_guard<std::mutex> lk(mu);
    for (auto& kv : sum) {
      size_t s = ensure(kv.first);
      float* r = rows.data() + s * dim;
      const float* g = kv.second.data();
      if (opt == 0) {
        for (int j = 0; j < dim; ++j) r[j] -= lr * g[j];
      } else if (opt == 1) {
        float* a = g2.data() + s * dim;
        for (int j = 0; j < dim; ++j) {
          a[j] += g[j] * g[j];
          r[j] -= lr * g[j] / (std::sqrt(a[j]) + eps);
        }
      } else {
        steps[s] += 1;
        double t = static_cast<double>(steps[s]);
        double c1 = 1.0 - std::pow(beta1, t);
        double c2 = 1.0 - std::pow(beta2, t);
        float* mm = m.data() + s * dim;
        float* vv = v.data() + s * dim;
        for (int j = 0; j < dim; ++j) {
          mm[j] = beta1 * mm[j] + (1.f - beta1) * g[j];
          vv[j] = beta2 * vv[j] + (1.f - beta2) * g[j] * g[j];
          double mh = mm[j] / c1, vh = vv[j] / c2;
          r[j] -= static_cast<float>(lr * mh / (std::sqrt(vh) + eps));
        }
      }
    }
  }

  // checkpoint: own binary format ("PTPS1"), written atomically
  // (tmp + rename). A table lives its whole life on one backend, so
  // this is NOT interchange format with the Python .npz shards —
  // restore a cpp checkpoint onto a cpp server.
  bool save(const char* path) {
    std::lock_guard<std::mutex> lk(mu);
    // mu serializes saves within this server; the pid qualifier keeps
    // two server PROCESSES checkpointing to one shared-fs path from
    // interleaving writes into the same tmp file
    std::string tmp = std::string(path) + ".tmp." +
                      std::to_string(::getpid());
    std::ofstream f(tmp, std::ios::binary | std::ios::trunc);
    if (!f) return false;
    const char magic[6] = {'P', 'T', 'P', 'S', '1', '\0'};
    int64_t n = static_cast<int64_t>(slot.size());
    f.write(magic, 6);
    f.write(reinterpret_cast<const char*>(&dim), sizeof(dim));
    f.write(reinterpret_cast<const char*>(&opt), sizeof(opt));
    f.write(reinterpret_cast<const char*>(&n), sizeof(n));
    for (const auto& kv : slot) {
      f.write(reinterpret_cast<const char*>(&kv.first), sizeof(int64_t));
      f.write(reinterpret_cast<const char*>(rows.data() + kv.second * dim),
              sizeof(float) * dim);
      if (opt == 1)
        f.write(reinterpret_cast<const char*>(g2.data() + kv.second * dim),
                sizeof(float) * dim);
      else if (opt == 2) {
        f.write(reinterpret_cast<const char*>(m.data() + kv.second * dim),
                sizeof(float) * dim);
        f.write(reinterpret_cast<const char*>(v.data() + kv.second * dim),
                sizeof(float) * dim);
        f.write(reinterpret_cast<const char*>(&steps[kv.second]),
                sizeof(int64_t));
      }
    }
    f.flush();
    if (!f) return false;
    f.close();
    // fsync before rename or the "crash never corrupts the previous
    // checkpoint" guarantee is a lie under delayed allocation (the
    // Python tier does flush+fsync for the same reason)
    int fd = ::open(tmp.c_str(), O_RDONLY);
    if (fd < 0) return false;
    bool synced = ::fsync(fd) == 0;
    ::close(fd);
    return synced && ::rename(tmp.c_str(), path) == 0;
  }

  bool load(const char* path) {
    // buffer + validate the WHOLE file before touching live state: a
    // truncated body must not leave a half-restored table being
    // served (the Python tier validates before mutating too)
    std::ifstream f(path, std::ios::binary | std::ios::ate);
    if (!f) return false;
    const auto fsize = static_cast<uint64_t>(f.tellg());
    f.seekg(0);
    char magic[6];
    int fdim, fopt;
    int64_t n;
    f.read(magic, 6);
    f.read(reinterpret_cast<char*>(&fdim), sizeof(fdim));
    f.read(reinterpret_cast<char*>(&fopt), sizeof(fopt));
    f.read(reinterpret_cast<char*>(&n), sizeof(n));
    if (!f || std::memcmp(magic, "PTPS1", 5) != 0 || fdim != dim ||
        fopt != opt || n < 0)
      return false;
    const uint64_t hdr = 6 + sizeof(fdim) + sizeof(fopt) + sizeof(n);
    uint64_t rec = sizeof(int64_t) + sizeof(float) * dim;  // id + row
    if (opt == 1) rec += sizeof(float) * dim;              // g2
    if (opt == 2) rec += 2 * sizeof(float) * dim + sizeof(int64_t);
    if (fsize != hdr + static_cast<uint64_t>(n) * rec) return false;
    std::vector<char> buf(static_cast<size_t>(n) * rec);
    f.read(buf.data(), static_cast<std::streamsize>(buf.size()));
    if (!f) return false;
    std::lock_guard<std::mutex> lk(mu);
    const char* p = buf.data();
    for (int64_t i = 0; i < n; ++i) {
      int64_t id;
      std::memcpy(&id, p, sizeof(id));
      p += sizeof(id);
      size_t s = ensure(id);
      std::memcpy(rows.data() + s * dim, p, sizeof(float) * dim);
      p += sizeof(float) * dim;
      if (opt == 1) {
        std::memcpy(g2.data() + s * dim, p, sizeof(float) * dim);
        p += sizeof(float) * dim;
      } else if (opt == 2) {
        std::memcpy(m.data() + s * dim, p, sizeof(float) * dim);
        p += sizeof(float) * dim;
        std::memcpy(v.data() + s * dim, p, sizeof(float) * dim);
        p += sizeof(float) * dim;
        std::memcpy(&steps[s], p, sizeof(int64_t));
        p += sizeof(int64_t);
      }
    }
    return true;
  }
};

struct Server {
  Table table;
  int listen_fd = -1;
  int port = 0;
  // SAVE/LOAD confinement (matches ps_impl.EmbeddingPSServer): any
  // path on loopback-bound servers, ckpt_root-contained paths
  // otherwise, rejected when non-loopback with no root configured
  bool loopback = false;
  std::string ckpt_root;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  // connection threads are DETACHED; we track their fds (to shutdown
  // on stop) and a live counter (to know when they have all exited) —
  // no unbounded vector of dead joinable threads
  std::mutex fd_mu;
  std::vector<int> conn_fds;
  std::atomic<int> live_conns{0};

  void shutdown_listener() {
    std::lock_guard<std::mutex> g(fd_mu);
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR);
  }
};

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

#pragma pack(push, 1)
struct Header {
  uint8_t op;
  uint16_t table;
  uint32_t n;
  uint32_t dim;
};
#pragma pack(pop)

bool send_msg(int fd, uint8_t op, uint16_t table, uint32_t n_ids,
              uint32_t dim, const void* ids, const void* payload,
              size_t payload_bytes) {
  Header h{op, table, n_ids, dim};
  uint32_t blen =
      static_cast<uint32_t>(n_ids * sizeof(int64_t) + payload_bytes);
  if (!write_all(fd, &h, sizeof(h))) return false;
  if (!write_all(fd, &blen, 4)) return false;
  if (n_ids && !write_all(fd, ids, n_ids * sizeof(int64_t))) return false;
  if (payload_bytes && !write_all(fd, payload, payload_bytes)) return false;
  return true;
}

bool path_in_root(const std::string& path, const std::string& root) {
  // realpath-resolve the candidate's DIRECTORY (the file itself may
  // not exist yet for SAVE) so a symlink under the root can't escape
  // it — matches the Python tier's os.path.realpath confinement
  auto slash = path.find_last_of('/');
  if (slash == std::string::npos || slash == 0) return false;
  std::string dir = path.substr(0, slash);
  std::string base = path.substr(slash + 1);
  if (base.empty() || base == "." || base == "..") return false;
  char resolved[PATH_MAX];
  if (!::realpath(dir.c_str(), resolved)) return false;
  std::string rdir(resolved);
  return rdir == root ||
         rdir.compare(0, root.size() + 1, root + "/") == 0;
}

void handle_conn(Server* srv, int fd) {
  for (;;) {
    Header h;
    uint32_t blen;
    if (!read_exact(fd, &h, sizeof(h)) || !read_exact(fd, &blen, 4)) break;
    constexpr uint32_t MAX_BODY = 1u << 30;
    if (blen > MAX_BODY) break;
    std::vector<char> body(blen);
    if (blen && !read_exact(fd, body.data(), blen)) break;
    // this server hosts exactly ONE table (shard-per-process model);
    // silently routing a nonzero table id into it would corrupt
    // embeddings across tables for a worker built with n_tables>1, so
    // reject the frame and drop the connection (the Python tier fails
    // loudly via tables[table] IndexError — match that strictness)
    if (h.table != 0) break;
    Table& t = srv->table;
    // strict body validation (the Python tier raises on shape
    // mismatch; a dim-mismatched client must not cause OOB reads)
    const uint64_t ids_bytes = static_cast<uint64_t>(h.n) * sizeof(int64_t);
    uint64_t want_payload = 0;
    if (h.op == OP_PUSH)
      want_payload = static_cast<uint64_t>(h.n) * t.dim * sizeof(float);
    if ((h.op == OP_PULL && blen != ids_bytes) ||
        (h.op == OP_PUSH && blen != ids_bytes + want_payload) ||
        ((h.op == OP_LEN || h.op == OP_STOP) && blen != 0) ||
        ((h.op == OP_SAVE || h.op == OP_LOAD) &&
         (h.n != 0 || h.dim != 0 || blen == 0 || blen >= MAX_PATH_LEN)))
      break;
    if (h.op == OP_SAVE || h.op == OP_LOAD) {
      std::string path(body.data(), blen);
      if (!srv->ckpt_root.empty()) {
        if (!path_in_root(path, srv->ckpt_root))
          break;  // outside the configured checkpoint root
      } else if (!srv->loopback) {
        break;    // network-reachable server with no root: refuse
      }
      bool ok = h.op == OP_SAVE ? t.save(path.c_str())
                                : t.load(path.c_str());
      if (!ok) break;  // client reads the drop as the failure signal
      if (!send_msg(fd, h.op, h.table, 0, 0, nullptr, nullptr, 0)) break;
      continue;
    }
    const auto* ids = reinterpret_cast<const int64_t*>(body.data());
    const auto* pay =
        reinterpret_cast<const float*>(body.data() + ids_bytes);
    if (h.op == OP_PULL) {
      std::vector<float> out(static_cast<size_t>(h.n) * t.dim);
      t.pull(ids, h.n, out.data());
      if (!send_msg(fd, OP_PULL, h.table, 0,
                    static_cast<uint32_t>(t.dim), nullptr, out.data(),
                    out.size() * sizeof(float)))
        break;
    } else if (h.op == OP_PUSH) {
      t.push(ids, h.n, pay);
      if (!send_msg(fd, OP_PUSH, h.table, 0, 0, nullptr, nullptr, 0)) break;
    } else if (h.op == OP_LEN) {
      int64_t sz;
      {
        std::lock_guard<std::mutex> g(t.mu);
        sz = static_cast<int64_t>(t.slot.size());
      }
      if (!send_msg(fd, OP_LEN, h.table, 1, 0, &sz, nullptr, 0)) break;
    } else if (h.op == OP_STOP) {
      send_msg(fd, OP_STOP, h.table, 0, 0, nullptr, nullptr, 0);
      srv->stopping.store(true);
      srv->shutdown_listener();  // wake the accept loop (fd_mu-guarded)
      break;
    } else {
      break;
    }
  }
  ::close(fd);
  {
    std::lock_guard<std::mutex> g(srv->fd_mu);
    auto& v = srv->conn_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  srv->live_conns.fetch_sub(1);
}

}  // namespace

extern "C" {

void* ptps_create(int dim, int opt, float lr, long long seed,
                  float init_scale, float beta1, float beta2, float eps) {
  auto* srv = new Server();
  srv->table.dim = dim;
  srv->table.opt = opt;
  srv->table.lr = lr;
  srv->table.seed = seed;
  srv->table.init_scale = init_scale;
  srv->table.beta1 = beta1;
  srv->table.beta2 = beta2;
  srv->table.eps = eps;
  return srv;
}

// bind + listen + spawn the accept loop; returns the bound port, or -1.
// host: dotted-quad interface to bind ("127.0.0.1" for loopback-only
// shards); NULL or "" binds all interfaces. The wire protocol is
// unauthenticated, so multi-host deployments assume a trusted network
// (docs/distributed.md) — loopback binding is the single-host default.
int ptps_serve(void* handle, const char* host, int port) {
  auto* srv = static_cast<Server*>(handle);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (host && host[0] &&
      ::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  srv->loopback =
      host && (std::strncmp(host, "127.", 4) == 0 ||
               std::strcmp(host, "localhost") == 0);
  srv->accept_thread = std::thread([srv] {
    while (!srv->stopping.load()) {
      int cfd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;
      {
        std::lock_guard<std::mutex> g(srv->fd_mu);
        srv->conn_fds.push_back(cfd);
      }
      srv->live_conns.fetch_add(1);
      std::thread(handle_conn, srv, cfd).detach();
    }
  });
  return srv->port;
}

void ptps_set_ckpt_root(void* handle, const char* dir) {
  static_cast<Server*>(handle)->ckpt_root = dir ? dir : "";
}

int ptps_save(void* handle, const char* path) {
  return static_cast<Server*>(handle)->table.save(path) ? 0 : -1;
}

int ptps_load(void* handle, const char* path) {
  return static_cast<Server*>(handle)->table.load(path) ? 0 : -1;
}

int ptps_stopping(void* handle) {
  return static_cast<Server*>(handle)->stopping.load() ? 1 : 0;
}

long long ptps_size(void* handle) {
  auto* srv = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> g(srv->table.mu);
  return static_cast<long long>(srv->table.slot.size());
}

void ptps_stop(void* handle) {
  auto* srv = static_cast<Server*>(handle);
  srv->stopping.store(true);
  srv->shutdown_listener();
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  {
    std::lock_guard<std::mutex> g(srv->fd_mu);
    if (srv->listen_fd >= 0) {
      ::close(srv->listen_fd);
      srv->listen_fd = -1;
    }
    // kick every open connection out of its blocking read — without
    // this, close() deadlocks while any client is still connected
    for (int fd : srv->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  // wait for the detached conn threads to drain (they must not touch
  // Server memory after ptps_destroy frees it)
  while (srv->live_conns.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
}

void ptps_destroy(void* handle) {
  ptps_stop(handle);
  delete static_cast<Server*>(handle);
}

}  // extern "C"
