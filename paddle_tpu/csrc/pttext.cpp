// libpttext: byte-level BPE tokenizer core.
//
// TPU-native framework runtime piece: tokenization is host-side, latency-
// critical for serving (the reference ships C++ tokenizers through
// paddlenlp/fast_tokenizer). This core does the encode hot loop in C++:
// greedy lowest-rank pair merging over a doubly-linked token list with a
// binary heap — O(n log n) per text. Python owns vocab construction and
// file formats; only raw tables cross the boundary.
//
// C ABI (ctypes): create / add_token / add_merge / finalize / encode /
// decode / destroy. Thread-safe after finalize (encode is read-only).

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<int32_t, int32_t>& p) const {
    return std::hash<uint64_t>()((uint64_t(uint32_t(p.first)) << 32) |
                                 uint32_t(p.second));
  }
};

struct Merge {
  int32_t merged_id;
  int32_t rank;
};

struct Tokenizer {
  // vocab: id -> bytes; bytes -> id
  std::vector<std::string> id_to_bytes;
  std::unordered_map<std::string, int32_t> bytes_to_id;
  // single-byte ids (initial segmentation)
  int32_t byte_ids[256];
  std::unordered_map<std::pair<int32_t, int32_t>, Merge, PairHash> merges;
  bool finalized = false;
};

struct HeapItem {
  int32_t rank;
  int32_t pos;      // index of left element in the node array
  uint64_t stamp;   // versioning: stale entries are skipped
  bool operator>(const HeapItem& o) const {
    return rank != o.rank ? rank > o.rank : pos > o.pos;
  }
};

struct Node {
  int32_t id;
  int32_t prev, next;
  uint64_t stamp;   // bumped on every mutation of this node
  bool alive;
};

}  // namespace

extern "C" {

void* pttok_create() { return new Tokenizer(); }

void pttok_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

// id must be dense-ish but any non-negative int works.
int pttok_add_token(void* h, const uint8_t* bytes, int64_t len, int32_t id) {
  auto* t = static_cast<Tokenizer*>(h);
  if (t->finalized || id < 0) return -1;
  std::string s(reinterpret_cast<const char*>(bytes), size_t(len));
  if (size_t(id) >= t->id_to_bytes.size()) t->id_to_bytes.resize(id + 1);
  t->id_to_bytes[id] = s;
  t->bytes_to_id.emplace(std::move(s), id);
  return 0;
}

int pttok_add_merge(void* h, int32_t left, int32_t right, int32_t merged,
                    int32_t rank) {
  auto* t = static_cast<Tokenizer*>(h);
  if (t->finalized) return -1;
  t->merges[{left, right}] = Merge{merged, rank};
  return 0;
}

int pttok_finalize(void* h) {
  auto* t = static_cast<Tokenizer*>(h);
  for (int b = 0; b < 256; ++b) {
    std::string s(1, char(b));
    auto it = t->bytes_to_id.find(s);
    t->byte_ids[b] = it == t->bytes_to_id.end() ? -1 : it->second;
  }
  t->finalized = true;
  return 0;
}

// Encode UTF-8/raw bytes -> token ids. Returns count (<= max_out) or -1.
int64_t pttok_encode(void* h, const uint8_t* text, int64_t len,
                     int32_t* out_ids, int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  if (!t->finalized) return -1;
  if (len == 0) return 0;

  std::vector<Node> nodes(static_cast<size_t>(len));
  for (int64_t i = 0; i < len; ++i) {
    int32_t id = t->byte_ids[text[i]];
    if (id < 0) return -1;  // vocab must cover all bytes (byte-level BPE)
    nodes[i] = Node{id, int32_t(i - 1), int32_t(i + 1), 0, true};
  }
  nodes.back().next = -1;

  std::priority_queue<HeapItem, std::vector<HeapItem>, std::greater<HeapItem>>
      heap;
  auto push_pair = [&](int32_t pos) {
    if (pos < 0) return;
    const Node& a = nodes[pos];
    if (!a.alive || a.next < 0) return;
    auto it = t->merges.find({a.id, nodes[a.next].id});
    if (it != t->merges.end())
      heap.push(HeapItem{it->second.rank, pos, a.stamp});
  };
  for (int64_t i = 0; i + 1 < len; ++i) push_pair(int32_t(i));

  while (!heap.empty()) {
    HeapItem item = heap.top();
    heap.pop();
    Node& a = nodes[item.pos];
    if (!a.alive || a.stamp != item.stamp || a.next < 0) continue;
    Node& b = nodes[a.next];
    auto it = t->merges.find({a.id, b.id});
    if (it == t->merges.end() || it->second.rank != item.rank) continue;
    // merge b into a
    a.id = it->second.merged_id;
    a.stamp++;
    b.alive = false;
    a.next = b.next;
    if (b.next >= 0) nodes[b.next].prev = item.pos;
    push_pair(item.pos);        // (merged, next)
    push_pair(a.prev);          // (prev, merged)
  }

  // walk the list from the head (node 0 is always the left survivor)
  int64_t n = 0;
  for (int32_t i = 0; i >= 0; i = nodes[i].next) {
    if (n >= max_out) return -2;
    out_ids[n++] = nodes[i].id;
  }
  return n;
}

// Decode ids -> bytes. Returns byte count (<= max_out) or -1/-2.
int64_t pttok_decode(void* h, const int32_t* ids, int64_t n, uint8_t* out,
                     int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || size_t(ids[i]) >= t->id_to_bytes.size()) return -1;
    const std::string& s = t->id_to_bytes[ids[i]];
    if (total + int64_t(s.size()) > max_out) return -2;
    memcpy(out + total, s.data(), s.size());
    total += int64_t(s.size());
  }
  return total;
}

}  // extern "C"
