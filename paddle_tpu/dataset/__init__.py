"""Legacy paddle.dataset API (reference: python/paddle/dataset/*).

Paddle 1.x exposed datasets as *readers* (zero-arg callables yielding
samples) — the counterpart of the paddle.reader decorators. This shim
keeps that surface, backed by the modern dataset classes in
paddle_tpu.vision.datasets / paddle_tpu.text (synthetic or local-file,
no downloads in this offline build). New code should use the Dataset /
DataLoader API directly.
"""
from __future__ import annotations

import numpy as np

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "common"]


class _ReaderModule:
    """mnist/cifar-style module face: .train() / .test() return readers."""

    def __init__(self, make_pairs):
        self._make_pairs = make_pairs

    def train(self, **kwargs):
        def rd():
            yield from self._make_pairs("train", **kwargs)
        return rd

    def test(self, **kwargs):
        def rd():
            yield from self._make_pairs("test", **kwargs)
        return rd


def _mnist_pairs(mode, **kwargs):
    from ..vision.datasets import MNIST
    ds = MNIST(mode=mode, **kwargs)
    for i in range(len(ds)):
        img, label = ds[i]
        yield np.asarray(img, np.float32).reshape(-1) / 255.0 * 2 - 1, \
            int(np.asarray(label).reshape(-1)[0])


def _cifar_pairs(mode, **kwargs):
    from ..vision.datasets import Cifar10
    ds = Cifar10(mode=mode, **kwargs)
    for i in range(len(ds)):
        img, label = ds[i]
        yield np.asarray(img, np.float32).reshape(-1) / 255.0, \
            int(np.asarray(label).reshape(-1)[0])


def _uci_pairs(mode, **kwargs):
    from ..text import UCIHousing
    ds = UCIHousing(mode=mode, **kwargs)
    for i in range(len(ds)):
        feat, target = ds[i]
        yield np.asarray(feat, np.float32), np.asarray(target, np.float32)


def _imdb_pairs(mode, **kwargs):
    from ..text import Imdb
    ds = Imdb(mode=mode, **kwargs)
    for i in range(len(ds)):
        doc, label = ds[i]
        yield doc, int(label)


mnist = _ReaderModule(_mnist_pairs)
cifar = _ReaderModule(_cifar_pairs)
# reference cifar module names: train10/test10/train100/test100
cifar.train10, cifar.test10 = cifar.train, cifar.test
uci_housing = _ReaderModule(_uci_pairs)
imdb = _ReaderModule(_imdb_pairs)


class common:  # reference dataset/common.py surface (md5/convert no-ops)
    @staticmethod
    def md5file(fname):
        import hashlib
        h = hashlib.md5()
        with open(fname, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
