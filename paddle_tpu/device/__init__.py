"""Device API (reference: python/paddle/device/__init__.py).

TPU is the first-class accelerator. CUDA entry points exist for API
parity and report unavailability — zero CUDA in this framework.
"""
from __future__ import annotations

import contextlib

import jax

from .._core.tensor import Place

_current_device = None


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, idx=0):
        super().__init__("tpu", idx)


class CUDAPlace(Place):  # parity shim
    def __init__(self, idx=0):
        super().__init__("gpu", idx)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class XPUPlace(Place):
    def __init__(self, idx=0):
        super().__init__("tpu", idx)


def _platform():
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def set_device(device):
    global _current_device
    _current_device = str(device)
    return get_device()


def get_device():
    if _current_device and _current_device.startswith("cpu"):
        return "cpu"
    plat = _platform()
    return f"{plat}:0" if plat != "cpu" else "cpu"


def get_all_device_type():
    return list({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return ["tpu"] if _platform() == "tpu" else []


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"tpu:{d.id}" for d in jax.devices()] if _platform() == "tpu" else []


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False  # XLA is the compiler; CINN does not exist here


def is_compiled_with_distribute():
    return True


def is_compiled_with_custom_device(device_type="tpu"):
    return device_type in ("tpu", "npu") and _platform() == "tpu"


def synchronize(device=None):
    """Block until all queued device work completes (TPU: drain async dispatch)."""
    try:
        (jax.device_put(0) + 0).block_until_ready()
    except Exception:
        pass


class Stream:
    """Parity shim: XLA:TPU executes a single ordered stream per core."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


@contextlib.contextmanager
def stream_guard(stream):
    yield


from . import cuda  # noqa: E402


def get_cudnn_version():
    """reference: device.get_cudnn_version — None when no cuDNN (always,
    on a TPU build)."""
    return None


class IPUPlace:
    """Another vendor's accelerator: importable for API parity, unusable
    by design (see static.ipu_shard_guard)."""

    def __init__(self, *a):
        pass

    def __repr__(self):
        return "IPUPlace() [unsupported on the TPU build]"


def set_stream(stream=None):
    """reference: device.set_stream — XLA owns stream scheduling on TPU;
    accepted and ignored (returns the previous 'stream', i.e. None)."""
    return None
