"""paddle.device.cuda parity shims — CUDA is absent by design (TPU build)."""
from __future__ import annotations

import jax


def device_count():
    return 0


def is_available():
    return False


def current_device():
    raise RuntimeError("paddle_tpu is a TPU build: CUDA is not available")


def get_device_name(device=None):
    return "TPU"


def get_device_capability(device=None):
    return (0, 0)


def max_memory_allocated(device=None):
    return 0


def max_memory_reserved(device=None):
    return 0


def memory_allocated(device=None):
    return 0


def memory_reserved(device=None):
    try:
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)
    except Exception:
        return 0


def empty_cache():
    pass


def synchronize(device=None):
    from . import synchronize as _sync
    _sync()
