"""paddle_tpu.distributed (reference: python/paddle/distributed/__init__.py)."""
from . import env  # noqa: F401
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, reduce_scatter, broadcast, broadcast_object_list,
    scatter, alltoall, alltoall_single, send, recv, barrier, reduce,
    get_backend, is_available, destroy_process_group, wait, p2p_ppermute,
)
from . import fleet  # noqa: F401
from . import ps  # noqa: F401
from . import rpc  # noqa: F401
from .parallel_wrappers import DataParallel  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Placement, Replicate, Shard, Partial, shard_tensor, reshard,
    shard_layer, dtensor_from_local, to_static, DistModel, shard_dataloader,
)
from ..parallel.mesh import create_mesh, get_mesh  # noqa: F401
from ..parallel.ring import ring_attention  # noqa: F401


def launch():
    raise RuntimeError(
        "paddle_tpu uses the single-controller JAX runtime: run one python "
        "process per host (multi-host: set JAX_COORDINATOR_ADDRESS & co, "
        "then init_parallel_env()); no launcher daemon is needed.")


def spawn(func, args=(), nprocs=-1, **options):
    """Single-controller: the mesh already spans local devices; run inline."""
    func(*args)


def get_device_count():
    return env.device_count()
from . import io  # noqa: E402,F401
from .extras import (  # noqa: E402,F401
    ParallelEnv, ParallelMode, ReduceType, DistAttr, gather,
    scatter_object_list, isend, irecv, gloo_init_parallel_env, gloo_barrier,
    gloo_release, split, dtensor_from_fn, unshard_dtensor, set_mesh,
    save_state_dict, load_state_dict, ShardingStage1, ShardingStage2,
    ShardingStage3, shard_optimizer, shard_scaler, Strategy, LocalLayer,
    parallelize, ColWiseParallel, RowWiseParallel, SequenceParallelBegin,
    SequenceParallelEnd, SequenceParallelEnable, SequenceParallelDisable,
    PrepareLayerInput, PrepareLayerOutput, SplitPoint, QueueDataset,
    InMemoryDataset, CountFilterEntry, ShowClickEntry, ProbabilityEntry,
    to_distributed,
)
