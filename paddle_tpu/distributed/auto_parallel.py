"""auto_parallel API (reference: python/paddle/distributed/auto_parallel/
api.py — shard_tensor/reshard/dtensor).

Direct mapping onto jax.sharding: ProcessMesh ≡ Mesh, Placement ≡
PartitionSpec entries, shard_tensor ≡ device_put with NamedSharding,
reshard ≡ device_put to a new sharding (XLA emits the collective).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._core.tensor import Tensor, Parameter, unwrap


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __init__(self, reduce_type=None):
        self.reduce_type = reduce_type


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._jax_mesh = Mesh(devs, tuple(self.dim_names))

    @property
    def mesh(self):
        return self._jax_mesh


def _spec_from_placements(ndim, placements, mesh):
    spec = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            spec[pl.dim] = mesh.axis_names[axis_i] if hasattr(mesh, "axis_names") \
                else mesh.dim_names[axis_i]
    return P(*spec)


def shard_tensor(data, mesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    t = data if isinstance(data, Tensor) else Tensor(jax.numpy.asarray(data))
    jmesh = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    spec = _spec_from_placements(t.ndim, placements, jmesh)
    sharded = jax.device_put(t._value, NamedSharding(jmesh, spec))
    out = Parameter(sharded, name=t.name) if isinstance(t, Parameter) \
        else Tensor(sharded, stop_gradient=t.stop_gradient if stop_gradient is None
                    else stop_gradient)
    out.dist_spec = spec
    return out


def reshard(dist_tensor, mesh, placements):
    jmesh = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    spec = _spec_from_placements(dist_tensor.ndim, placements, jmesh)
    out = Tensor(jax.device_put(dist_tensor._value, NamedSharding(jmesh, spec)),
                 stop_gradient=dist_tensor.stop_gradient)
    out.dist_spec = spec
    return out


def dtensor_from_local(local_tensor, mesh, placements):
    return shard_tensor(local_tensor, mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    raise NotImplementedError("use paddle_tpu.parallel.Trainer (round 2: facade)")
