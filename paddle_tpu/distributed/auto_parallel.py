"""auto_parallel API (reference: python/paddle/distributed/auto_parallel/
api.py — shard_tensor/reshard/dtensor).

Direct mapping onto jax.sharding: ProcessMesh ≡ Mesh, Placement ≡
PartitionSpec entries, shard_tensor ≡ device_put with NamedSharding,
reshard ≡ device_put to a new sharding (XLA emits the collective).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._core.tensor import Tensor, Parameter, unwrap


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __init__(self, reduce_type=None):
        self.reduce_type = reduce_type


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._jax_mesh = Mesh(devs, tuple(self.dim_names))

    @property
    def mesh(self):
        return self._jax_mesh


def _spec_from_placements(ndim, placements, mesh):
    spec = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            spec[pl.dim] = mesh.axis_names[axis_i] if hasattr(mesh, "axis_names") \
                else mesh.dim_names[axis_i]
    return P(*spec)


def shard_tensor(data, mesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    t = data if isinstance(data, Tensor) else Tensor(jax.numpy.asarray(data))
    jmesh = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    spec = _spec_from_placements(t.ndim, placements, jmesh)
    sharded = jax.device_put(t._value, NamedSharding(jmesh, spec))
    out = Parameter(sharded, name=t.name) if isinstance(t, Parameter) \
        else Tensor(sharded, stop_gradient=t.stop_gradient if stop_gradient is None
                    else stop_gradient)
    out.dist_spec = spec
    return out


def reshard(dist_tensor, mesh, placements):
    jmesh = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    spec = _spec_from_placements(dist_tensor.ndim, placements, jmesh)
    out = Tensor(jax.device_put(dist_tensor._value, NamedSharding(jmesh, spec)),
                 stop_gradient=dist_tensor.stop_gradient)
    out.dist_spec = spec
    return out


def dtensor_from_local(local_tensor, mesh, placements):
    return shard_tensor(local_tensor, mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def _mesh_from_layer(layer):
    """Mesh the layer's parameters were placed on (via shard_tensor), the
    fleet global mesh, or None (single device)."""
    for _, p in layer.named_parameters():
        sh = getattr(unwrap(p), "sharding", None)
        if isinstance(sh, NamedSharding):
            return sh.mesh
    from . import env
    return env.get_global_mesh()


class DistModel:
    """reference: distributed/auto_parallel/api.py DistModel — the object
    `to_static` returns. Calling it in train mode runs one compiled
    hybrid-parallel step (loss returned); in eval mode computes the loss
    without updating; in predict mode returns outputs."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None):
        from ..parallel.trainer import Trainer

        self._layer = layer
        self._loss = loss
        self._mode = "train"
        mesh = _mesh_from_layer(layer)
        bspec = None
        if mesh is not None and "dp" in mesh.shape and mesh.shape["dp"] > 1:
            bspec = P("dp")  # prefix spec: every batch leaf dp-sharded

        def trainer_loss(model, batch):
            *inputs, labels = batch
            out = model(*inputs)
            return loss(out, labels)

        self._trainer = Trainer(layer, optimizer, trainer_loss, mesh=mesh,
                                batch_spec=bspec)

    def train(self):
        self._mode = "train"
        self._layer.train()

    def eval(self):
        self._mode = "eval"
        self._layer.eval()

    def predict(self):
        self._mode = "predict"
        self._layer.eval()

    def dist_main_program(self, mode=None):  # parity introspection hooks
        return None

    def state_dict(self, mode="all"):
        self._trainer.sync_model()
        return self._layer.state_dict()

    def __call__(self, *args):
        if self._mode == "train":
            return self._trainer.step(tuple(args))
        self._trainer.sync_model()
        if self._mode == "predict":
            return self._layer(*args)
        *inputs, labels = args
        return self._loss(self._layer(*inputs), labels)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """reference: python/paddle/distributed/auto_parallel/api.py:2988.

    Compiles the (layer, loss, optimizer) triple into a single jitted
    hybrid-parallel train step over the mesh the layer's parameters were
    shard_tensor-placed on. Returns a DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy)


def shard_dataloader(dataloader, meshes=None, shard_dims=None,
                     input_keys=None):
    """reference api.py shard_dataloader: under the single-controller JAX
    model each host iterates the global batch and `to_static` shards it
    onto the mesh (dp prefix spec), so the loader passes through."""
    return dataloader
