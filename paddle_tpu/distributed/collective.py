"""Collective communication API (reference: python/paddle/distributed/
collective.py + communication/*).

TPU-native: collectives are XLA ops over mesh axes (psum/all_gather/
ppermute/all_to_all riding ICI), not NCCL calls. Inside shard_map the
paddle API maps 1:1 onto lax collectives via the `group` → axis-name
mapping. Outside SPMD regions (pure eager, single process) they act on
replicated values (identity semantics), matching world_size==1 behavior.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .._core.tensor import Tensor, apply, unwrap
from . import env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Process-group parity object: names a mesh axis."""

    def __init__(self, axis_name=None, ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.id = id

    @property
    def nranks(self):
        if self.axis_name is None:
            return env.get_world_size()
        return len(self.ranks) if self.ranks else env.device_count()

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        return env.get_rank()

    def get_group_rank(self, rank):
        return rank

    @property
    def process_group(self):
        return self


_default_group = Group()


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    return Group(axis_name=axis_name, ranks=ranks, id=np.random.randint(1 << 30))


def get_group(gid=0):
    return _default_group


def _axis(group):
    if group is None:
        return None
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", None)


def _in_spmd(x):
    return isinstance(x, jax.core.Tracer)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)
    raw = unwrap(tensor)
    if ax is not None and _in_spmd(raw):
        fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
              ReduceOp.MIN: lax.pmin,
              ReduceOp.AVG: lambda v, a: lax.pmean(v, a)}.get(op, lax.psum)
        out = fn(raw, ax)
        if isinstance(tensor, Tensor):
            tensor._replace(out)
            return tensor
        return out
    return tensor  # replicated / world_size==1: identity


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    ax = _axis(group)
    raw = unwrap(tensor)
    if ax is not None and _in_spmd(raw):
        out = lax.all_gather(raw, ax)
        if isinstance(tensor_list, list):
            n = out.shape[0]
            tensor_list.extend(Tensor(out[i]) for i in range(n))
            return tensor_list
        return out
    if isinstance(tensor_list, list):
        tensor_list.append(tensor.clone() if isinstance(tensor, Tensor) else tensor)
        return tensor_list
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group)
    raw = unwrap(tensor)
    if ax is not None and _in_spmd(raw):
        out = lax.psum_scatter(raw, ax, scatter_dimension=0, tiled=True)
        if isinstance(tensor, Tensor):
            tensor._replace(out)
            return tensor
        return out
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    return tensor  # replicated semantics


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        v = tensor_list[env.get_rank() if env.get_rank() < len(tensor_list) else 0]
        if isinstance(tensor, Tensor):
            tensor._replace(unwrap(v))
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group)
    if isinstance(in_tensor_list, Tensor) or (
            not isinstance(in_tensor_list, (list, tuple))):
        raw = unwrap(in_tensor_list)
        if ax is not None and _in_spmd(raw):
            n = lax.axis_size(ax)
            out = lax.all_to_all(raw.reshape((n, -1) + raw.shape[1:]), ax, 0, 0,
                                 tiled=False)
            return Tensor(out.reshape(raw.shape)) if isinstance(in_tensor_list,
                                                                Tensor) else out
        return in_tensor_list
    if out_tensor_list is not None:
        out_tensor_list.extend(t.clone() for t in in_tensor_list)
        return out_tensor_list
    return list(in_tensor_list)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis(group)
    raw = unwrap(in_tensor)
    if ax is not None and _in_spmd(raw):
        n = lax.axis_size(ax)
        out = lax.all_to_all(raw, ax, split_axis=0, concat_axis=0, tiled=True)
        if out_tensor is not None and isinstance(out_tensor, Tensor):
            out_tensor._replace(out)
            return out_tensor
        return out
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._replace(raw)
        return out_tensor
    return in_tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError("point-to-point send/recv outside shard_map is not a "
                       "TPU primitive; use ppermute inside shard_map "
                       "(paddle_tpu.distributed.p2p_ppermute)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError("use ppermute inside shard_map (p2p_ppermute)")


def p2p_ppermute(x, perm, axis_name):
    """Ring/point-to-point transfer inside shard_map: lax.ppermute."""
    return lax.ppermute(unwrap(x), axis_name, perm)


def barrier(group=None):
    (jax.device_put(0) + 0).block_until_ready()


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def get_backend(group=None):
    return "xla"  # ICI/DCN via XLA collectives; NCCL does not exist here


def is_available():
    return True


def destroy_process_group(group=None):
    pass


def wait(tensor, group=None, use_calc_stream=True):
    raw = unwrap(tensor)
    if hasattr(raw, "block_until_ready"):
        raw.block_until_ready()
    return tensor
