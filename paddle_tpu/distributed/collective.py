"""Collective communication API (reference: python/paddle/distributed/
collective.py + communication/*).

TPU-native: collectives are XLA ops over mesh axes (psum/all_gather/
ppermute/all_to_all riding ICI), not NCCL calls. Inside shard_map the
paddle API maps 1:1 onto lax collectives via the `group` → axis-name
mapping. Outside SPMD regions (pure eager, single process) they act on
replicated values (identity semantics), matching world_size==1 behavior.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .._core.compat import axis_size

from .._core.tensor import Tensor, apply, unwrap
from . import env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Process-group parity object: names a mesh axis."""

    def __init__(self, axis_name=None, ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.id = id

    @property
    def nranks(self):
        if self.axis_name is None:
            return env.get_world_size()
        return len(self.ranks) if self.ranks else env.device_count()

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        return env.get_rank()

    def get_group_rank(self, rank):
        return rank

    @property
    def process_group(self):
        return self


_default_group = Group()


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    return Group(axis_name=axis_name, ranks=ranks, id=np.random.randint(1 << 30))


def get_group(gid=0):
    return _default_group


def _axis(group):
    if group is None:
        return None
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", None)


def _in_spmd(x):
    return isinstance(x, jax.core.Tracer)


def _eager_mesh_axes(raw, ax):
    """For a concrete array: (mesh, spec, axes-to-reduce) if it carries a
    NamedSharding whose mesh can serve the requested communication, else
    (None, None, ()) for the degenerate single-participant case. Raises
    when communication was explicitly requested but cannot happen —
    silently returning the input would corrupt multi-device math."""
    from jax.sharding import NamedSharding

    sharding = getattr(raw, "sharding", None)
    if isinstance(sharding, NamedSharding):
        mesh = sharding.mesh
        spec = sharding.spec
        used = set()
        for entry in spec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                used.add(a)
        if ax is not None:
            if ax not in mesh.shape:
                raise RuntimeError(
                    f"collective over axis {ax!r}: tensor's mesh has axes "
                    f"{tuple(mesh.shape)}; cannot communicate over a "
                    f"nonexistent axis")
            axes = (ax,) if ax in used else ()
            if mesh.shape[ax] > 1 and ax not in used:
                # replicated over the axis: reduction is size * value for
                # SUM — still well-defined; treat as all-shards-equal
                axes = (ax,)
            return mesh, spec, axes
        return mesh, spec, tuple(a for a in mesh.axis_names if a in used)
    if ax is not None:
        raise RuntimeError(
            f"collective over axis {ax!r} called on an unsharded tensor "
            f"outside shard_map: no mesh to communicate over. Place the "
            f"tensor with a NamedSharding or call inside shard_map/jit.")
    if env.get_world_size() > 1:
        raise RuntimeError(
            "collective on an unsharded tensor in a multi-process run: "
            "cross-host eager collectives are not supported; use mesh-"
            "sharded arrays or shard_map.")
    return None, None, ()


def _drop_axes(spec, axes):
    """PartitionSpec with `axes` removed (those dims become replicated)."""
    from jax.sharding import PartitionSpec as P
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a not in axes)
            out.append(kept if kept else None)
        else:
            out.append(None if entry in axes else entry)
    return P(*out)


def _eager_psum(raw, op, mesh, spec, axes):
    """Real reduction of a sharded eager array: each shard is one
    participant (paddle rank semantics); result is the reduced shard,
    replicated over the reduced axes."""
    from .._core.compat import shard_map

    fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
          ReduceOp.MIN: lax.pmin, ReduceOp.AVG: lax.pmean}.get(op)
    if fn is None:
        raise NotImplementedError(
            f"all_reduce op {op!r} has no XLA collective mapping "
            f"(SUM/MAX/MIN/AVG supported)")
    reduced = shard_map(lambda s: fn(s, axes), mesh=mesh,
                        in_specs=(spec,), out_specs=_drop_axes(spec, axes))(raw)
    return reduced


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)
    raw = unwrap(tensor)
    if _in_spmd(raw):
        if ax is None:
            return tensor  # traced but no axis: replicated value
        fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
              ReduceOp.MIN: lax.pmin,
              ReduceOp.AVG: lambda v, a: lax.pmean(v, a)}.get(op)
        if fn is None:
            raise NotImplementedError(
                f"all_reduce op {op!r} has no XLA collective mapping "
                f"(SUM/MAX/MIN/AVG supported)")
        out = fn(raw, ax)
        if isinstance(tensor, Tensor):
            tensor._replace(out)
            return tensor
        return out
    mesh, spec, axes = _eager_mesh_axes(raw, ax)
    if mesh is None or not axes:
        return tensor  # world of one participant: reduction is identity
    out = _eager_psum(raw, op, mesh, spec, axes)
    if isinstance(tensor, Tensor):
        tensor._replace(out)
        return tensor
    return out


def _resolve_group_axis(mesh, spec, axes, ax, opname):
    """The single mesh axis a collective communicates over, or raise —
    multi-axis layouts need an explicit group and a dim sharded by
    exactly that axis (contiguous split is wrong otherwise)."""
    a = ax if ax is not None else (axes[0] if len(axes) == 1 else None)
    if a is None:
        raise RuntimeError(
            f"{opname}: tensor is sharded over multiple axes {axes}; "
            f"pass group=<axis name> to pick the group")
    dim = _sharded_dim(spec, (a,))
    if dim is not None:
        entry = spec[dim]
        ents = entry if isinstance(entry, tuple) else (entry,)
        if tuple(e for e in ents if e is not None) != (a,):
            raise RuntimeError(
                f"{opname} over {a!r}: dim {dim} is sharded over {ents}; "
                f"participant shards are not contiguous along a "
                f"multi-axis dim")
    return a, dim


def _sharded_dim(spec, axes):
    """First tensor dim partitioned over one of `axes` (None if none)."""
    for i, entry in enumerate(spec):
        ents = entry if isinstance(entry, tuple) else (entry,)
        if any(a in axes for a in ents if a is not None):
            return i
    return None


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    ax = _axis(group)
    raw = unwrap(tensor)
    if _in_spmd(raw):
        if ax is None:
            if isinstance(tensor_list, list):
                tensor_list.append(tensor)
                return tensor_list
            return tensor
        out = lax.all_gather(raw, ax)
        if isinstance(tensor_list, list):
            n = out.shape[0]
            tensor_list.extend(Tensor(out[i]) for i in range(n))
            return tensor_list
        return out
    mesh, spec, axes = _eager_mesh_axes(raw, ax)
    if mesh is not None and axes and isinstance(tensor_list, list):
        # each participant's tensor is its shard; replicated-over-axis
        # tensors contribute n identical copies (paddle: every rank's copy)
        a, dim = _resolve_group_axis(mesh, spec, axes, ax, "all_gather")
        n = mesh.shape[a]
        if dim is not None:
            pieces = jnp.split(raw, n, axis=dim)
            tensor_list.extend(Tensor(p) for p in pieces)
        else:
            tensor_list.extend(Tensor(raw) for _ in range(n))
        return tensor_list
    if isinstance(tensor_list, list):
        tensor_list.append(tensor.clone() if isinstance(tensor, Tensor) else tensor)
        return tensor_list
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group)
    raw = unwrap(tensor)
    if _in_spmd(raw):
        if ax is None:
            return tensor
        out = lax.psum_scatter(raw, ax, scatter_dimension=0, tiled=True)
        if isinstance(tensor, Tensor):
            tensor._replace(out)
            return tensor
        return out
    mesh, spec, axes = _eager_mesh_axes(raw, ax)
    if mesh is not None and axes:
        from .._core.compat import shard_map
        a, dim = _resolve_group_axis(mesh, spec, axes, ax, "reduce_scatter")
        if dim != 0:
            raise NotImplementedError(
                f"eager reduce_scatter needs dim 0 sharded over the group "
                f"axis {a!r} (got sharded dim {dim}); out_specs for other "
                f"layouts would mislabel the scattered result")
        out = shard_map(
            lambda s: lax.psum_scatter(s, a, scatter_dimension=0, tiled=True),
            mesh=mesh, in_specs=(spec,), out_specs=spec)(raw)
        if isinstance(tensor, Tensor):
            tensor._replace(out)
            return tensor
        return out
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)
    raw = unwrap(tensor)
    if _in_spmd(raw):
        return tensor  # inside shard_map: value already per-device
    mesh, spec, axes = _eager_mesh_axes(raw, ax)
    if mesh is not None and axes:
        # every participant's shard becomes src's shard, along ONE group
        # axis (src indexes ranks of that axis)
        a, dim = _resolve_group_axis(mesh, spec, axes, ax, "broadcast")
        n = mesh.shape[a]
        if dim is not None:
            if not 0 <= src < n:
                raise ValueError(
                    f"broadcast src={src} out of range for group axis "
                    f"{a!r} of size {n}")
            piece = jnp.split(raw, n, axis=dim)[src]
            out = jnp.concatenate([piece] * n, axis=dim)
            out = jax.device_put(out, raw.sharding)
            if isinstance(tensor, Tensor):
                tensor._replace(out)
                return tensor
            return out
    return tensor  # replicated over the group: already src's value


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        v = tensor_list[env.get_rank() if env.get_rank() < len(tensor_list) else 0]
        if isinstance(tensor, Tensor):
            tensor._replace(unwrap(v))
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group)
    if isinstance(in_tensor_list, Tensor) or (
            not isinstance(in_tensor_list, (list, tuple))):
        raw = unwrap(in_tensor_list)
        if ax is not None and _in_spmd(raw):
            n = axis_size(ax)
            out = lax.all_to_all(raw.reshape((n, -1) + raw.shape[1:]), ax, 0, 0,
                                 tiled=False)
            return Tensor(out.reshape(raw.shape)) if isinstance(in_tensor_list,
                                                                Tensor) else out
        return in_tensor_list
    if out_tensor_list is not None:
        out_tensor_list.extend(t.clone() for t in in_tensor_list)
        return out_tensor_list
    return list(in_tensor_list)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis(group)
    raw = unwrap(in_tensor)
    if ax is not None and _in_spmd(raw):
        n = axis_size(ax)
        out = lax.all_to_all(raw, ax, split_axis=0, concat_axis=0, tiled=True)
        if out_tensor is not None and isinstance(out_tensor, Tensor):
            out_tensor._replace(out)
            return out_tensor
        return out
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._replace(raw)
        return out_tensor
    return in_tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError("point-to-point send/recv outside shard_map is not a "
                       "TPU primitive; use ppermute inside shard_map "
                       "(paddle_tpu.distributed.p2p_ppermute)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError("use ppermute inside shard_map (p2p_ppermute)")


def p2p_ppermute(x, perm, axis_name):
    """Ring/point-to-point transfer inside shard_map: lax.ppermute."""
    return lax.ppermute(unwrap(x), axis_name, perm)


def barrier(group=None):
    # blocking IS the contract of a barrier
    (jax.device_put(0) + 0).block_until_ready()  # tpulint: disable=TPL005 -- explicit barrier API


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def get_backend(group=None):
    return "xla"  # ICI/DCN via XLA collectives; NCCL does not exist here


def is_available():
    return True


def destroy_process_group(group=None):
    pass


def wait(tensor, group=None, use_calc_stream=True):
    raw = unwrap(tensor)
    if hasattr(raw, "block_until_ready"):
        raw.block_until_ready()  # tpulint: disable=TPL005 -- comm.wait() is an explicit fence
    return tensor
