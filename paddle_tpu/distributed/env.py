"""Distributed environment state (reference: python/paddle/distributed/
parallel.py env + fleet topology).

Single-controller JAX model: one python process drives all local TPU
chips; multi-host uses jax.distributed. "rank" = process index (for data
sharding); intra-process parallelism is expressed on the global mesh.
"""
from __future__ import annotations

import os
import threading

import jax

_state = threading.local()
_global_mesh = None
_hybrid_topology = None


def init_parallel_env():
    """reference: paddle.distributed.init_parallel_env. Multi-host init is
    driven by env vars set by paddle_tpu.distributed.launch.

    jax.distributed.initialize() only auto-detects the coordinator on known
    cluster environments (GKE/Cloud TPU metadata); on a bare launch the
    JAX_NUM_PROCESSES / JAX_PROCESS_ID vars our launcher exports are NOT
    read by jax itself, so pass them explicitly. A failed rendezvous must
    raise: silently continuing would run N independent single-process
    trainers that all see the same data shard and produce wrong results.
    """
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    # NB: must not call jax.process_count() (or anything else that
    # initializes the XLA backend) before jax.distributed.initialize —
    # initialize() refuses to run after backend init, which would make
    # every real rendezvous fail. Probe the distributed client directly.
    try:
        already = jax.distributed.is_initialized()
    except Exception:
        already = False
    if coord and not already:
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        try:
            if nproc is not None and pid is not None:
                jax.distributed.initialize(coordinator_address=coord,
                                           num_processes=int(nproc),
                                           process_id=int(pid))
            else:
                jax.distributed.initialize(coordinator_address=coord)
        except Exception as e:
            raise RuntimeError(
                f"init_parallel_env: jax.distributed.initialize failed "
                f"(coordinator={coord}, num_processes={nproc}, "
                f"process_id={pid}). Refusing to continue as a "
                f"single-process trainer inside a multi-host launch.") from e
    return get_rank()


def get_rank(group=None):
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group=None):
    try:
        return jax.process_count()
    except Exception:
        return 1


def is_initialized():
    return True


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def set_global_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh


def get_global_mesh():
    return _global_mesh


def set_topology(topo):
    global _hybrid_topology
    _hybrid_topology = topo


def get_topology():
    return _hybrid_topology


def inside_shard_map():
    """True when executing under shard_map/pjit manual axes (collectives
    with axis names are legal)."""
    try:
        from jax.core import get_axis_env  # may vary across jax versions
    except Exception:
        get_axis_env = None
    try:
        frame = jax.core.unsafe_get_axis_names() if \
            hasattr(jax.core, "unsafe_get_axis_names") else []
        return bool(frame)
    except Exception:
        return False
