"""Remaining paddle.distributed surface (reference: python/paddle/
distributed/__init__.py __all__): legacy env objects, dtensor auxiliary
APIs, the `parallelize` plan classes, and PS-era dataset/entry configs.

Single-controller SPMD translation: "process group" notions map onto mesh
axes; anything that only exists to coordinate multi-process CPU servers
(gloo, parameter-server datasets) is a documented shim pointing at the
mesh-native path (see distributed/ps.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap
from . import env
from .collective import all_gather, barrier


# ---------------------------------------------------------------- legacy env
class ParallelEnv:
    """reference: parallel.ParallelEnv (legacy env object)."""

    @property
    def rank(self):
        return env.get_rank()

    @property
    def world_size(self):
        return env.get_world_size()

    @property
    def device_id(self):
        try:
            return jax.devices()[0].id
        except Exception:
            return 0

    @property
    def current_endpoint(self):
        import os
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        import os
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


class ParallelMode:
    """reference: fleet.base.topology.ParallelMode constants."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    """reference: auto_parallel ReduceType (partial placements)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """reference: legacy static DistAttr — carries (mesh, sharding_specs)
    for a tensor; superseded by NamedSharding placements here."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs or []


# ------------------------------------------------------------- collectives+
def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """reference: communication/gather.py — like all_gather but only dst
    keeps the result. Single-controller SPMD sees every shard, so this is
    all_gather with the destination-rank convention kept for parity."""
    out = []
    all_gather(out, tensor, group=group)
    if gather_list is not None and env.get_rank() == dst:
        gather_list.clear()
        gather_list.extend(out)
    return out if env.get_rank() == dst else None


def scatter_object_list(out_object_list, in_object_list, src=0, group=None):
    """reference: communication/scatter.py — rank r receives
    in_object_list[r]."""
    r = env.get_rank()
    if in_object_list is None or not len(in_object_list):
        raise ValueError("scatter_object_list: empty in_object_list")
    out_object_list.clear()
    out_object_list.append(in_object_list[min(r, len(in_object_list) - 1)])


def isend(tensor, dst=0, group=None):
    raise RuntimeError(
        "point-to-point isend/irecv is not a TPU primitive; use "
        "lax.ppermute inside shard_map (distributed.p2p_ppermute) — the "
        "pipeline schedule in parallel/pp.py shows the pattern")


def irecv(tensor, src=0, group=None):
    raise RuntimeError(
        "use lax.ppermute inside shard_map (distributed.p2p_ppermute)")


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: parallel gloo bootstrap (CPU rendezvous for PS mode).
    jax.distributed handles host rendezvous here — nothing to start."""
    return None


def gloo_barrier():
    barrier()


def gloo_release():
    return None


# ------------------------------------------------------- megatron split op
def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: fleet/layers/mpu/mp_layers via paddle.distributed.split
    — build a row/column-partitioned linear or embedding over the model-
    parallel axis. Returns the layer's output for input x (paddle's
    functional form constructs the layer internally)."""
    from ..parallel.tp import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:  # split columns of the weight
            layer = ColumnParallelLinear(in_f, out_f,
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(in_f, out_f,
                                      input_is_parallel=False)
        return layer(x)
    if operation == "embedding":
        vocab, hidden = size
        layer = VocabParallelEmbedding(vocab, hidden)
        return layer(x)
    raise ValueError(f"split: unknown operation {operation!r}")


# --------------------------------------------------------- dtensor helpers
def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """reference: auto_parallel api.dtensor_from_fn."""
    from .auto_parallel import shard_tensor
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(dist_tensor):
    """reference: auto_parallel api.unshard_dtensor — gather to a dense
    replicated tensor."""
    v = unwrap(dist_tensor)
    return Tensor(jnp.asarray(jax.device_get(v)))


def set_mesh(mesh):
    env.set_global_mesh(mesh)


def get_mesh():
    return env.get_global_mesh()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_name=True):
    """reference: auto_parallel checkpoint save — each host writes its
    shards; single-controller writes one file."""
    from ..framework.io import save
    save({k: (v if isinstance(v, Tensor) else Tensor(jnp.asarray(v)))
          for k, v in state_dict.items()}, path)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_name=True):
    from ..framework.io import load
    loaded = load(path)
    for k in list(state_dict):
        if k in loaded:
            v = loaded[k]
            state_dict[k] = v if isinstance(v, Tensor) else \
                Tensor(jnp.asarray(v))
    return state_dict


# ------------------------------------------------- sharding (ZeRO) markers
class ShardingStage1:
    """Marker/shard_fn for shard_optimizer (reference sharding api)."""
    stage = 1

    def __init__(self, axis=None, mesh=None):
        self.axis, self.mesh = axis, mesh


class ShardingStage2(ShardingStage1):
    stage = 2


class ShardingStage3(ShardingStage1):
    stage = 3


def shard_optimizer(optimizer, shard_fn=None):
    """reference: auto_parallel api.shard_optimizer — mark the optimizer
    so the Trainer shards its slots (ZeRO); the actual sharding specs are
    derived from the stage at Trainer build time."""
    stage = getattr(shard_fn, "stage", 1) if shard_fn is not None else 1
    optimizer._sharding_stage = stage
    return optimizer


def shard_scaler(scaler):
    """reference: api.shard_scaler — GradScaler state is replicated (the
    found-inf reduction rides the grad psum), nothing extra to shard."""
    return scaler


# ----------------------------------------------------- parallelize planner
class _Plan:
    def __init__(self, gather_output=False):
        self.gather_output = gather_output


class ColWiseParallel(_Plan):
    """Shard Linear weight columns over 'tp' (reference mp plan)."""
    spec = ("cols",)


class RowWiseParallel(_Plan):
    spec = ("rows",)


class SequenceParallelBegin(_Plan):
    spec = ("sp_begin",)


class SequenceParallelEnd(_Plan):
    spec = ("sp_end",)


class SequenceParallelEnable(_Plan):
    spec = ("sp",)


class SequenceParallelDisable(_Plan):
    spec = ("sp_off",)


class PrepareLayerInput(_Plan):
    def __init__(self, fn=None):
        super().__init__()
        self.fn = fn


class PrepareLayerOutput(_Plan):
    def __init__(self, fn=None):
        super().__init__()
        self.fn = fn


class SplitPoint:
    """Pipeline split markers (reference pp plan)."""
    BEGINNING = "beginning"
    END = "end"


class Strategy:
    """reference: auto_parallel Strategy — config bag; consumed by
    to_static/parallelize."""

    def __init__(self, config=None):
        self.sharding = type("C", (), {"enable": False, "stage": 1,
                                       "degree": -1})()
        self.fused_passes = type("C", (), {"enable": False})()
        self.pipeline = type("C", (), {"enable": False, "schedule_mode":
                                       "1F1B", "micro_batch_size": 1})()
        self.gradient_merge = type("C", (), {"enable": False, "k_steps": 1})()
        if config:
            for k, v in config.items():
                setattr(self, k, v)


def parallelize(model, optimizer=None, mesh=None, config=None):
    """reference: auto_parallel api.parallelize — apply a plan dict
    {sublayer-name-pattern: plan} (mp_config/pp_config/dp_config) by
    setting dist_spec placements on matching parameters; the jitted
    Trainer/GSPMD does the rest."""
    from jax.sharding import PartitionSpec as P
    config = config or {}
    mp = (config.get("mp_config") or {}).get("parallelize_plan", {})
    for pattern, plan in mp.items():
        for name, sub in model.named_sublayers():
            if not _name_match(name, pattern):
                continue
            w = getattr(sub, "weight", None)
            if w is None:
                continue
            if isinstance(plan, ColWiseParallel):
                w.dist_spec = P(None, "tp")
                b = getattr(sub, "bias", None)
                if b is not None:
                    b.dist_spec = P("tp")
            elif isinstance(plan, RowWiseParallel):
                w.dist_spec = P("tp", None)
    if optimizer is not None and (config.get("dp_config") or {}):
        optimizer._sharding_stage = 2
    return (model, optimizer) if optimizer is not None else model


def _name_match(name, pattern):
    import re
    rx = re.escape(pattern).replace(r"\*", ".*")
    return re.fullmatch(rx, name) is not None or name.endswith(pattern)


class LocalLayer:
    """reference: auto_parallel LocalLayer — a layer whose forward runs
    per-device inside shard_map with declared out placements. Here plain
    composition: subclass nn.Layer and annotate outputs yourself; kept as
    an alias base for API parity."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)

    def __new__(cls, *a, **kw):
        from ..nn.layer.layers import Layer
        if cls is LocalLayer:
            raise TypeError("subclass LocalLayer together with nn.Layer")
        return super().__new__(cls)


def to_distributed(model, optimizer, dataloader, device_num=None,
                   node_num=1):
    """reference: auto_parallel high-level to_distributed — single-
    controller SPMD needs no wrapping: ensure a mesh exists and return
    the triple; the Trainer reads placements from the model."""
    from ..parallel.mesh import get_mesh as _gm, create_mesh
    if _gm() is None:
        n = device_num or jax.device_count()
        env.set_global_mesh(create_mesh({"dp": n}))
    return model, optimizer, dataloader


# ------------------------------------------------------ PS-era data configs
_PS_MSG = ("the PS streaming dataset pipeline is out of TPU scope: feed "
           "with paddle_tpu.io.DataLoader instead. (PS *tables* are "
           "supported — host-RAM sparse embeddings via distributed/ps "
           "SparseTable/DistributedEmbedding; dense params train on the "
           "mesh: VocabParallelEmbedding / MoE all_to_all)")


class QueueDataset:
    """reference: distributed/ps QueueDataset (streaming PS reader)."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_MSG)


class InMemoryDataset:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_MSG)


class CountFilterEntry:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_MSG)


class ShowClickEntry:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_MSG)


class ProbabilityEntry:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_MSG)
