"""fleet facade (reference: python/paddle/distributed/fleet/fleet.py).

fleet.init(strategy) builds the global TPU mesh from
hybrid_configs degrees; distributed_model/distributed_optimizer return
mesh-aware wrappers. The NCCL HybridCommunicateGroup becomes axis-name
bookkeeping over one jax.sharding.Mesh.
"""
from __future__ import annotations

import os

import numpy as np
import jax

from .. import env as _env
from ...parallel.mesh import create_mesh


class DistributedStrategy:
    """reference: paddle.distributed.fleet.DistributedStrategy (protobuf);
    here a plain config object with the same field names."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.dgc = False
        self.find_unused_parameters = False


class HybridCommunicateGroup:
    """Topology parity (reference: fleet/base/topology.py), backed by mesh
    axis bookkeeping instead of NCCL comm groups."""

    def __init__(self, mesh):
        self._mesh = mesh
        self._shape = dict(mesh.shape)

    def _axis(self, name, default=1):
        return self._shape.get(name, default)

    def get_data_parallel_world_size(self):
        return self._axis("dp")

    def get_model_parallel_world_size(self):
        return self._axis("tp")

    def get_pipe_parallel_world_size(self):
        return self._axis("pp")

    def get_sharding_parallel_world_size(self):
        return self._axis("dp")  # sharding rides the dp axis

    def get_data_parallel_rank(self):
        return 0  # single-controller: ranks are mesh coordinates, not processes

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_data_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="dp")

    def get_model_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="tp")

    def get_pipe_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="pp")

    def get_sharding_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="dp")

    def get_check_parallel_group(self, *a):
        from ..collective import Group
        return Group()

    def topology(self):
        return self._shape


class PaddleCloudRoleMaker:
    """Role resolution for parameter-server launches (reference:
    fleet/base/role_maker.py — reads TRAINING_ROLE & co from the cloud
    launcher). Ours reads PT_PS_ROLE (preferred) or TRAINING_ROLE:
    'server'/'pserver' puts this process in the server tier, anything
    else makes it a worker."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        role = os.environ.get("PT_PS_ROLE",
                              os.environ.get("TRAINING_ROLE", "worker"))
        self._role = role.lower()

    def is_server(self):
        return not self._is_collective and \
            self._role in ("server", "pserver", "ps")

    def is_worker(self):
        return not self.is_server()


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._mesh = None
        self._is_initialized = False
        self._role_maker = None

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        self._role_maker = role_maker
        if role_maker is not None and role_maker.is_server():
            # PS server tier: no TPU mesh — the process only hosts
            # host-RAM SparseTable shards (distributed/ps_impl.py)
            self._strategy = strategy or DistributedStrategy()
            self._is_initialized = True
            return self
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        n = jax.device_count()
        dp = int(hc.get("dp_degree", 1) or 1)
        tp = int(hc.get("mp_degree", 1) or 1)
        pp = int(hc.get("pp_degree", 1) or 1)
        used = dp * tp * pp
        if used != n:
            if n % (tp * pp) == 0:
                dp = n // (tp * pp)
            else:
                tp = pp = 1
                dp = n
        axes = {}
        if pp > 1:
            axes["pp"] = pp
        axes["dp"] = dp
        if tp > 1:
            axes["tp"] = tp
        if len(axes) == 1 and "dp" in axes:
            axes = {"dp": dp}
        self._mesh = create_mesh(axes)
        self._hcg = HybridCommunicateGroup(self._mesh)
        _env.set_topology(self._hcg)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return _env.get_rank() == 0

    def worker_index(self):
        return _env.get_rank()

    def worker_num(self):
        return _env.get_world_size()

    def get_hybrid_communicate_group(self):
        return self._hcg

    def get_mesh(self):
        return self._mesh

    def pipeline_schedule(self):
        """Normalized pipeline schedule from
        strategy.pipeline_configs['schedule_mode'] (reference:
        fleet/meta_optimizers/pipeline_optimizer.py:55 — 'F-then-B' is
        GPipe, '1F1B' is one-forward-one-backward) combined with
        hybrid_configs['pp_configs'/'virtual_pp_degree' (reference:
        pipeline_parallel.py:1309 interleaved virtual stages). Consumed
        by models.llama_spmd.make_train_step(schedule=None)."""
        cfgs = getattr(self._strategy, "pipeline_configs", None) or {}
        mode = str(cfgs.get("schedule_mode", "F-then-B"))
        table = {"1f1b": "1f1b", "f-then-b": "gpipe",
                 "interleave": "interleave"}
        if mode.lower() not in table:
            # never silently downgrade: a user who asked for a schedule
            # we don't implement must not discover it via an OOM from
            # the wrong memory profile
            raise ValueError(
                f"pipeline_configs schedule_mode={mode!r} is not "
                "supported: use '1F1B', 'F-then-B' (GPipe), or "
                "'interleave'")
        sched = table[mode.lower()]
        if sched == "1f1b" and self.virtual_pp_degree() > 1:
            # reference semantics: 1F1B + virtual_pp_degree>1 IS the
            # interleaved schedule
            sched = "interleave"
        return sched

    def virtual_pp_degree(self):
        """hybrid_configs virtual pipeline degree (vpp chunks per
        stage); 1 = plain schedules."""
        hc = getattr(self._strategy, "hybrid_configs", None) or {}
        pp_cfgs = hc.get("pp_configs") or {}
        if isinstance(pp_cfgs, dict) and "virtual_pp_degree" in pp_cfgs:
            return int(pp_cfgs["virtual_pp_degree"] or 1)
        return int(hc.get("virtual_pp_degree", 1) or 1)

    def distributed_model(self, model):
        from ..parallel_wrappers import DataParallel
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return optimizer

    def distributed_scaler(self, scaler):
        return scaler

    @property
    def worker_endpoints(self):
        return [f"proc{i}" for i in range(_env.get_world_size())]

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    # ---- parameter-server role entry points (reference: fleet.init_server/
    # run_server/init_worker/stop_worker driving the_one_ps.TheOnePSRuntime;
    # ours delegate to distributed/ps_impl.py — see docs/distributed.md)
    def is_server(self):
        return self._role_maker is not None and self._role_maker.is_server()

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def init_server(self, tables=None, **kw):
        from .. import ps
        return ps.init_server(tables, **kw)

    def run_server(self):
        from .. import ps
        return ps.run_server()

    def init_worker(self, n_tables=1):
        from .. import ps
        return ps.init_worker(n_tables)

    def stop_worker(self):
        # role_maker-less processes count as workers (the hybrid flow:
        # collective dense SPMD + PT_PS_ENDPOINTS sparse tables) — their
        # PS client sockets must close too
        if self.is_worker():
            from .. import ps
            ps.stop_worker()

    def save_inference_model(self, *a, **k):
        pass

    def save_persistables(self, *a, **k):
        pass


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_server = fleet.is_server
is_worker = fleet.is_worker
init_server = fleet.init_server
run_server = fleet.run_server
init_worker = fleet.init_worker
stop_worker = fleet.stop_worker


class UserDefinedRoleMaker:
    """Explicit-role variant (reference: fleet/base/role_maker.py):
    role is 'server'/'pserver' or 'worker' (case-insensitive)."""

    def __init__(self, current_id=0, role="worker", worker_num=1,
                 server_endpoints=None, **k):
        self.current_id = current_id
        self.worker_num = worker_num
        self.server_endpoints = server_endpoints or []
        self._role = str(role).lower()

    def is_server(self):
        return self._role in ("server", "pserver", "ps")

    def is_worker(self):
        return not self.is_server()

# NB: PaddleCloudRoleMaker (env-driven roles) is defined above _Fleet.


from ...parallel.pp import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: E402,F401
from ...parallel.tp import (  # noqa: E402,F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)


class meta_parallel:
    """Namespace parity: fleet.meta_parallel.* layers."""
    ColumnParallelLinear = ColumnParallelLinear
    RowParallelLinear = RowParallelLinear
    VocabParallelEmbedding = VocabParallelEmbedding
    ParallelCrossEntropy = ParallelCrossEntropy
    LayerDesc = LayerDesc
    SharedLayerDesc = SharedLayerDesc
    PipelineLayer = PipelineLayer


def recompute(function, *args, **kwargs):
    """reference: fleet.recompute — activation rematerialization. On TPU
    this is jax.checkpoint over the pure functional core; when `function`
    is a Layer its parameters are threaded through so grads flow."""
    import jax as _jax
    from ..._core.tensor import Tensor, apply
    from ...nn.layer.layers import Layer

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    n_args = len(tensor_args)
    if isinstance(function, Layer):
        pnames = [n for n, _ in function.named_parameters()]
        ptensors = [p for _, p in function.named_parameters()]
    else:
        pnames, ptensors = [], []

    def pure(*raws):
        it = iter(raws[:n_args])
        rebuilt = [Tensor(next(it), stop_gradient=a.stop_gradient)
                   if isinstance(a, Tensor) else a for a in args]
        param_map = dict(zip(pnames, raws[n_args:]))
        if isinstance(function, Layer):
            with function._swapped_state(param_map, None):
                out = function(*rebuilt, **kwargs)
        else:
            out = function(*rebuilt, **kwargs)
        return _jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    ck = _jax.checkpoint(pure)
    return apply(ck, *(tensor_args + ptensors), name="recompute")


# ---- remaining reference __all__ surface --------------------------------
Fleet = _Fleet  # class name export (reference: base/fleet_base.Fleet)


class Role:
    """reference: fleet Role enum (PS-era: WORKER/SERVER)."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class CommunicateTopology:
    """reference: fleet.base.topology.CommunicateTopology — named-axis
    rank bookkeeping; here a thin view over the hybrid mesh axes."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        n = 1
        for d in self._dims:
            n *= d
        return n

    def get_rank(self, **axis_ranks):
        rank, stride = 0, 1
        for name, dim in zip(reversed(self._names), reversed(self._dims)):
            rank += axis_ranks.get(name, 0) * stride
            stride *= dim
        return rank

    def get_coord(self, rank):
        coord = []
        for name, dim in zip(reversed(self._names), reversed(self._dims)):
            coord.append(rank % dim)
            rank //= dim
        return list(reversed(coord))


class UtilBase:
    """reference: fleet.UtilBase — rank-0 barrier/all-gather utilities."""

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _barrier
        _barrier()

    def all_gather(self, input, comm_world="worker"):
        from ..collective import all_gather_object
        out = []
        all_gather_object(out, input)
        return out

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..collective import all_reduce, ReduceOp
        from ..._core.tensor import Tensor
        import numpy as _np
        import jax.numpy as _jnp
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}.get(mode, ReduceOp.SUM)
        t = input if isinstance(input, Tensor) else \
            Tensor(_jnp.asarray(_np.asarray(input)))
        out = all_reduce(t, op=op)
        return out if isinstance(input, Tensor) else \
            _np.asarray(out.numpy()).tolist()

    def get_file_shard(self, files):
        r, w = _env.get_rank(), max(_env.get_world_size(), 1)
        return files[r::w]

    def print_on_rank(self, message, rank_id=0):
        if _env.get_rank() == rank_id:
            print(message)


_PS_DATAGEN_MSG = ("MultiSlot*DataGenerator feeds the PS streaming dataset "
                   "pipeline — out of TPU scope; pack samples with "
                   "io.DataLoader / io/native.py instead (PS sparse tables "
                   "themselves ARE supported: distributed/ps "
                   "SparseTable/DistributedEmbedding)")


class MultiSlotDataGenerator:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_DATAGEN_MSG)


class MultiSlotStringDataGenerator:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_PS_DATAGEN_MSG)


from . import utils  # noqa: E402,F401  (fleet.utils: LocalFS/HDFSClient/recompute)
