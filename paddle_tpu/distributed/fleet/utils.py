"""paddle.distributed.fleet.utils parity (reference:
python/paddle/distributed/fleet/utils/{__init__,fs}.py).

`recompute` is the fleet-level activation-rematerialization entry (the
real implementation lives in fleet.__init__ over jax.checkpoint).
`LocalFS` is the filesystem client the checkpoint/elastic tooling uses.
`HDFSClient` shells out to the hadoop CLI when present — this image is
zero-egress with no hadoop, so construction succeeds (config parity)
and operations raise with guidance. `DistributedInfer` belongs to the
fluid static-graph PS-inference flow; its job here is
inference.Predictor (+ the PS tier for sparse tables), so it raises
with that guidance.
"""
from __future__ import annotations

import os
import shutil

from . import recompute  # noqa: F401  (reference re-exports it here)

__all__ = ["LocalFS", "HDFSClient", "DistributedInfer", "recompute"]


class FSFileExistsError(RuntimeError):
    pass


class FSFileNotExistsError(RuntimeError):
    pass


class LocalFS:
    """reference fs.py:141 — local filesystem with the FS client
    interface (so checkpoint code can take any FS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, f))
             else files).append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            os.remove(fs_path)
        else:
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        return self.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [f for f in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, f))]


class HDFSClient:
    """reference fs.py HDFSClient — drives `hadoop fs` via the CLI.
    Constructed with config for parity; operations require the hadoop
    binary, absent in this zero-egress image."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self.hadoop_home = hadoop_home
        self.configs = dict(configs or {})
        self.time_out = time_out
        self.sleep_inter = sleep_inter
        cand = (os.path.join(hadoop_home, "bin", "hadoop")
                if hadoop_home else "hadoop")
        self._bin = shutil.which(cand) or (
            cand if os.path.exists(cand) else None)

    def _unavailable(self, op):
        if self._bin is None:
            raise RuntimeError(
                f"HDFSClient.{op}: no hadoop CLI on this host. Point "
                "hadoop_home at a hadoop install, or use LocalFS / "
                "object storage for checkpoints.")
        raise NotImplementedError(
            f"HDFSClient.{op}: driving `hadoop fs` is not implemented in "
            "paddle_tpu (checkpointing targets LocalFS / object "
            f"storage); found hadoop at {self._bin} but no shell "
            "bindings exist")

    # explicit stubs (not __getattr__ magic): hasattr()/getattr(...,
    # default) probes must behave normally, and a host WITH hadoop
    # gets honest guidance instead of a bare AttributeError
    def ls_dir(self, fs_path):
        self._unavailable("ls_dir")

    def is_file(self, fs_path):
        self._unavailable("is_file")

    def is_dir(self, fs_path):
        self._unavailable("is_dir")

    def is_exist(self, fs_path):
        self._unavailable("is_exist")

    def upload(self, local_path, fs_path):
        self._unavailable("upload")

    def upload_dir(self, local_dir, dest_dir):
        self._unavailable("upload_dir")

    def download(self, fs_path, local_path):
        self._unavailable("download")

    def mkdirs(self, fs_path):
        self._unavailable("mkdirs")

    def delete(self, fs_path):
        self._unavailable("delete")

    def rename(self, fs_src_path, fs_dst_path):
        self._unavailable("rename")

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        self._unavailable("mv")

    def touch(self, fs_path, exist_ok=True):
        self._unavailable("touch")

    def cat(self, fs_path=None):
        self._unavailable("cat")

    def list_dirs(self, fs_path):
        self._unavailable("list_dirs")

    def need_upload_download(self):
        return True


class DistributedInfer:
    """reference utils/__init__.py DistributedInfer — fluid static-graph
    PS inference orchestration."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "DistributedInfer drives the fluid static-graph PS inference "
            "flow; on paddle_tpu use paddle_tpu.inference.Predictor for "
            "dense models and the distributed.ps tier for sparse tables")
