"""paddle.distributed.io (reference: python/paddle/distributed/io.py —
persistables save/load for trainer checkpoints)."""
from __future__ import annotations

import os

from ..framework.io import save as _save, load as _load

__all__ = ["save_persistables", "load_persistables", "is_persistable",
           "save_inference_model", "load_inference_model_distributed"]


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def save_persistables(executor=None, dirname=".", main_program=None,
                      filename=None):
    """Persist every registered persistable var of the program (or the
    layer passed as main_program)."""
    state = {}
    if main_program is not None and hasattr(main_program, "state_dict"):
        state = main_program.state_dict()
    elif main_program is not None and hasattr(main_program, "_vars"):
        state = {k: v for k, v in main_program._vars.items()
                 if is_persistable(v)}
    os.makedirs(dirname, exist_ok=True)
    _save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor=None, dirname=".", main_program=None,
                      filename=None):
    path = os.path.join(dirname, filename or "persistables.pdparams")
    state = _load(path)
    if main_program is not None and hasattr(main_program, "set_state_dict"):
        main_program.set_state_dict(state)
    return state


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, **kw):
    from ..static import save_inference_model as _sim
    return _sim(os.path.join(dirname, "model"), feeded_var_names,
                target_vars, executor, program=main_program)


def load_inference_model_distributed(dirname, executor, **kw):
    from ..static import load_inference_model as _lim
    return _lim(os.path.join(dirname, "model"), executor)
