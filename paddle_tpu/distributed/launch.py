"""paddle.distributed.launch parity for TPU pods.

Reference: python/paddle/distributed/launch (per-GPU process spawn, elastic
restarts). TPU redesign: JAX is single-controller-per-host — one process
drives all local chips — so "launch" means per-HOST process bootstrap:

    python -m paddle_tpu.distributed.launch \
        --nnodes 4 --node_rank $RANK --coordinator host0:8476 train.py ...

sets the jax.distributed env (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID) and execs the script; `init_parallel_env()` inside the script
completes the rendezvous. `--max_restarts N` gives elastic fault
tolerance: a crashed trainer is relaunched (it resumes from its own
checkpoints — see utils.checkpoint save_state/load_state).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def build_env(nnodes, node_rank, coordinator, base_env=None):
    env = dict(base_env if base_env is not None else os.environ)
    if nnodes > 1:
        env["JAX_COORDINATOR_ADDRESS"] = coordinator
        env["JAX_NUM_PROCESSES"] = str(nnodes)
        env["JAX_PROCESS_ID"] = str(node_rank)
        # paddle-style aliases some user code expects
        env["PADDLE_TRAINERS_NUM"] = str(nnodes)
        env["PADDLE_TRAINER_ID"] = str(node_rank)
    return env


def run(script_argv, nnodes=1, node_rank=0, coordinator="127.0.0.1:8476",
        max_restarts=0, restart_backoff=3.0, env=None):
    """Run the training script; returns its final exit code."""
    child_env = build_env(nnodes, node_rank, coordinator, env)
    attempt = 0
    while True:
        proc = subprocess.run([sys.executable] + list(script_argv),
                              env=child_env)
        if proc.returncode == 0 or attempt >= max_restarts:
            return proc.returncode
        attempt += 1
        print(f"[launch] trainer exited rc={proc.returncode}; "
              f"restart {attempt}/{max_restarts} in {restart_backoff}s",
              file=sys.stderr)
        time.sleep(restart_backoff)


def main(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int, default=int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1)))
    p.add_argument("--node_rank", type=int, default=int(
        os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--coordinator", "--master", dest="coordinator",
                   default=os.environ.get("PADDLE_MASTER",
                                          "127.0.0.1:8476"))
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("script", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.script:
        p.error("no training script given")
    return run(args.script, args.nnodes, args.node_rank, args.coordinator,
               args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
