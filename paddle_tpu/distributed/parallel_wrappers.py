"""DataParallel wrapper (reference: python/paddle/distributed/parallel.py).

On TPU, data parallelism is batch sharding over the 'dp' mesh axis; the
grad allreduce the reference does via NCCL hooks is inserted by GSPMD
when the Trainer's batch in_sharding is P('dp'). This wrapper keeps the
paddle API shape and annotates batch inputs.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .._core.tensor import Tensor, apply
from ..nn.layer.layers import Layer
from . import env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        mesh = env.get_global_mesh()
        if mesh is not None and "dp" in mesh.shape:
            def shard_batch(t):
                if isinstance(t, Tensor) and t.ndim >= 1:
                    def fn(a):
                        try:
                            spec = [None] * a.ndim
                            spec[0] = "dp"
                            return jax.lax.with_sharding_constraint(
                                a, NamedSharding(mesh, P(*spec)))
                        except Exception:
                            return a
                    return apply(fn, t, name="dp_shard")
                return t
            inputs = tuple(shard_batch(i) for i in inputs)
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass  # GSPMD inserts the grad psum

    @property
    def _layers_attr(self):
        return self._layers

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)
