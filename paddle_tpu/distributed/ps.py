"""Parameter-server mode — explicit out-of-scope facade.

Reference: python/paddle/distributed/ps/the_one_ps.py (TheOnePSRuntime:
CPU parameter servers + trainer workers exchanging sparse/dense grads
over DCN/BRPC).

Design decision (documented, not a TODO): the PS architecture exists to
scale *sparse* embedding tables beyond worker memory on commodity
ethernet. On a TPU pod the same workloads are served by the SPMD path —
embedding tables sharded over the mesh with XLA all-to-all on ICI (see
parallel/tp.py VocabParallelEmbedding and parallel/moe.py), which is
both faster and simpler than an external server tier; DCN-attached
python parameter servers would bottleneck a pod. Every entry point here
raises with that guidance rather than pretending to run.
"""
from __future__ import annotations

_MSG = ("parameter-server mode is not part of the TPU execution model: "
        "sparse/giant embedding tables are sharded over the device mesh "
        "(VocabParallelEmbedding / fleet sharding) with XLA collectives "
        "over ICI instead of an external server tier. Use "
        "fleet.init(is_collective=True) and mesh sharding; see "
        "docs/distributed.md.")


class TheOnePSRuntime:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


class PsProgramBuilder:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


def init_server(*a, **k):
    raise NotImplementedError(_MSG)


def init_worker(*a, **k):
    raise NotImplementedError(_MSG)


def run_server(*a, **k):
    raise NotImplementedError(_MSG)


def stop_worker(*a, **k):
    raise NotImplementedError(_MSG)
