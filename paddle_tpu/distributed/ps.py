"""Parameter-server mode (reference: python/paddle/distributed/ps/
the_one_ps.py TheOnePSRuntime — CPU parameter servers + trainer workers
exchanging sparse/dense grads over BRPC).

TPU-native split of that architecture (see ps_impl.py for the full
design notes):

* DENSE parameters never use a server tier — they train on the SPMD
  path (mesh-sharded, XLA collectives over ICI), which is faster and
  simpler than external servers on a pod. fleet.init(is_collective=True)
  + mesh sharding is the recommended path for everything that fits HBM.
* SPARSE host-RAM tables (rec-sys embeddings beyond collective HBM) are
  the one PS job the mesh cannot do, and that part is implemented:
  sharded SparseTable servers with per-row sgd/adagrad/adam, TCP
  pull/push, and a DistributedEmbedding worker layer that feeds pulled
  rows through a jitted step and pushes the row-gradient back.
"""
from paddle_tpu.distributed.ps_impl import (  # noqa: F401
    DistributedEmbedding,
    CppPSServer,
    EmbeddingPSServer,
    PSClient,
    SparseTable,
    TheOnePSRuntime,
    init_server,
    init_worker,
    run_server,
    shard_of,
    sparse_embedding_step,
    stop_worker,
)

__all__ = [
    "CppPSServer", "DistributedEmbedding", "EmbeddingPSServer", "PSClient",
    "SparseTable",
    "TheOnePSRuntime", "init_server", "init_worker", "run_server",
    "shard_of", "sparse_embedding_step", "stop_worker",
]
