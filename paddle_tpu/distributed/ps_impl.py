"""Parameter-server runtime, TPU-native re-design.

Reference: python/paddle/distributed/ps/the_one_ps.py (TheOnePSRuntime)
+ paddle/fluid/distributed/ps/service/brpc_ps_server.cc — CPU parameter
servers holding sparse embedding tables, trainer workers pulling rows
and pushing per-row gradients over RPC, servers applying per-row
optimizer rules (async SGD family).

TPU-native re-design (NOT a port of the BRPC stack):

* Dense parameters never leave the device mesh — they train on the SPMD
  path (VocabParallelEmbedding / fleet sharding over ICI). The PS tier
  exists for ONE job the mesh cannot do: sparse tables larger than
  collective HBM (rec-sys embeddings, 100 GB+). Those rows live in host
  RAM, sharded by id across server processes.
* The worker step is the host/device split jax makes natural: unique
  the batch ids on host, PULL rows, feed them to the jitted step as a
  plain input, take the row-gradient OUT of the step as a plain output,
  PUSH it back. No side effects inside jit, no custom_vjp tricks — the
  pulled rows are just another (trainable) input, so the same step
  compiles once and reruns for any id set of the same unique-count.
* Transport: length-prefixed binary over TCP sockets (threaded server,
  one shard lock per table — concurrent workers give the reference's
  async-SGD semantics). In-process shards (no sockets) are the default
  when no endpoints are configured: single-host training and tests run
  the identical table/optimizer code without the network.

Per-row optimizer rules: sgd, adagrad, adam (per-row state, lazily
materialized rows with deterministic seeded init so any server
restart / re-shard reproduces untouched rows).
"""
from __future__ import annotations

import os
import socket
import socketserver
import struct
import sys
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

__all__ = [
    "SparseTable", "PSClient", "EmbeddingPSServer", "CppPSServer",
    "DistributedEmbedding",
    "sparse_embedding_step", "init_server", "run_server", "init_worker",
    "stop_worker", "TheOnePSRuntime", "shard_of",
]


_save_seq = 0
_save_seq_lock = threading.Lock()


def shard_of(ids, n_shards):
    """Server shard owning each id (stable modulo placement)."""
    return np.asarray(ids) % n_shards


# ---------------------------------------------------------------------------
# server-side sparse table (one shard)
# ---------------------------------------------------------------------------


class SparseTable:
    """One shard of a host-RAM embedding table with per-row optimizer.

    Rows materialize on first pull (reference sparse tables are keyed
    hash tables, not dense arrays): id -> slot index into growing numpy
    arrays. Unseen rows are initialized deterministically from
    (seed, id) so restarts and re-shards reproduce them exactly.

    Feature-entry accessor (reference: CtrAccessor config in
    the_one_ps.proto / ps/utils/ps_program_builder.py): with
    entry_threshold > 0, a row's embedding only participates after its
    feature has been SEEN that many times. Each PULL counts one show
    per occurrence (a pull = the feature appeared in a batch — the
    analogue of the reference's pushed show signal); below-threshold
    pulls return zeros and below-threshold pushes drop their gradient,
    so one-off junk features never materialize trainable state.
    show_decay_rate < 1 ages show counts via decay_shows() (call once
    per pass/epoch); shrink() then drops rows whose decayed count fell
    below threshold — the reference's table shrink for bounding
    rec-sys table growth.
    """

    GROW = 1024

    def __init__(self, dim, optimizer="adagrad", lr=0.01, seed=0,
                 init_scale=0.01, beta1=0.9, beta2=0.999, eps=1e-8,
                 entry_threshold=0, show_decay_rate=1.0):
        self.dim = int(dim)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown sparse optimizer: {optimizer!r}")
        self.lr, self.seed, self.init_scale = float(lr), int(seed), init_scale
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.entry_threshold = float(entry_threshold)
        self.show_decay_rate = float(show_decay_rate)
        self._slot = {}                       # id -> row index
        self._rows = np.empty((0, dim), np.float32)
        self._state = {}                      # name -> per-row state array
        self._steps = np.empty((0,), np.int64)  # adam bias-correction t
        self._shows = np.empty((0,), np.float32)  # accessor show counts
        self._lock = threading.Lock()
        if optimizer == "adagrad":
            self._state["g2"] = np.empty((0, dim), np.float32)
        elif optimizer == "adam":
            self._state["m"] = np.empty((0, dim), np.float32)
            self._state["v"] = np.empty((0, dim), np.float32)

    def __len__(self):
        return len(self._slot)

    def _init_row(self, id_):
        rng = np.random.RandomState((self.seed * 0x9E3779B1 + id_)
                                    & 0x7FFFFFFF)
        return (rng.randn(self.dim) * self.init_scale).astype(np.float32)

    def _ensure(self, ids):
        """Slot indices for ids, materializing unseen rows. Lock held."""
        new = [i for i in ids if i not in self._slot]
        if new:
            n0, n1 = len(self._slot), len(self._slot) + len(new)
            if n1 > len(self._rows):
                cap = max(n1, len(self._rows) + self.GROW)
                self._rows = np.resize(self._rows, (cap, self.dim))
                for k in self._state:
                    st = np.resize(self._state[k], (cap, self.dim))
                    st[n0:] = 0.0
                    self._state[k] = st
                self._steps = np.resize(self._steps, (cap,))
                self._steps[n0:] = 0
                self._shows = np.resize(self._shows, (cap,))
                self._shows[n0:] = 0.0
            for j, id_ in enumerate(new):
                self._slot[id_] = n0 + j
                self._rows[n0 + j] = self._init_row(id_)
                for k in self._state:
                    self._state[k][n0 + j] = 0.0
                self._steps[n0 + j] = 0
                self._shows[n0 + j] = 0.0
        return np.fromiter((self._slot[i] for i in ids), np.int64,
                           count=len(ids))

    def pull(self, ids):
        """rows (n, dim) for int64 ids (duplicates allowed). Each pull
        counts one show per occurrence; below-threshold rows read as
        zeros (embedding not yet created, reference CtrAccessor entry
        semantics)."""
        ids = np.asarray(ids, np.int64)
        accessor_on = self.entry_threshold > 0 or self.show_decay_rate < 1.0
        with self._lock:
            idx = self._ensure(ids.tolist())
            if accessor_on:   # np.add.at is slow; skip when feature off
                np.add.at(self._shows, idx, 1.0)
            out = self._rows[idx].copy()
            if self.entry_threshold > 0:
                out[self._shows[idx] < self.entry_threshold] = 0.0
            return out

    def push(self, ids, grads):
        """Apply per-row rule to summed-by-id gradients (scatter-add:
        duplicate ids in one push contribute once at their summed
        gradient, matching dense embedding backward semantics)."""
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        if grads.shape != (len(ids), self.dim):
            raise ValueError(f"push shape {grads.shape} != "
                             f"({len(ids)}, {self.dim})")
        uniq, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(g, inv, grads)
        with self._lock:
            idx = self._ensure(uniq.tolist())
            if self.entry_threshold > 0:
                # below-threshold rows: the pull returned zeros, so the
                # incoming gradient is for an embedding that does not
                # exist yet — drop it (the show was already counted)
                live = self._shows[idx] >= self.entry_threshold
                if not live.all():
                    idx, g = idx[live], g[live]
                    if not len(idx):
                        return
            if self.optimizer == "sgd":
                self._rows[idx] -= self.lr * g
            elif self.optimizer == "adagrad":
                g2 = self._state["g2"]
                g2[idx] += g * g
                self._rows[idx] -= self.lr * g / (np.sqrt(g2[idx]) + self.eps)
            else:  # adam
                self._steps[idx] += 1
                t = self._steps[idx][:, None].astype(np.float32)
                m, v = self._state["m"], self._state["v"]
                m[idx] = self.beta1 * m[idx] + (1 - self.beta1) * g
                v[idx] = self.beta2 * v[idx] + (1 - self.beta2) * g * g
                mhat = m[idx] / (1 - self.beta1 ** t)
                vhat = v[idx] / (1 - self.beta2 ** t)
                self._rows[idx] -= self.lr * mhat / (np.sqrt(vhat) + self.eps)

    def decay_shows(self, rate=None):
        """Age every row's show count (reference: CtrAccessor
        show_click_decay_rate, applied once per pass)."""
        rate = self.show_decay_rate if rate is None else float(rate)
        with self._lock:
            self._shows *= rate

    def shrink(self, threshold=None):
        """Drop rows whose (decayed) show count fell below threshold —
        the reference's table shrink. Returns #rows dropped. Surviving
        rows keep their optimizer state; dropped ids re-materialize
        from the deterministic init if seen again."""
        threshold = self.entry_threshold if threshold is None \
            else float(threshold)
        with self._lock:
            keep = [(i, s) for i, s in self._slot.items()
                    if self._shows[s] >= threshold]
            dropped = len(self._slot) - len(keep)
            if not dropped:
                return 0
            old_idx = np.asarray([s for _, s in keep], np.int64)
            self._slot = {i: j for j, (i, _) in enumerate(keep)}
            n = len(keep)
            self._rows[:n] = self._rows[old_idx]
            for k in self._state:
                self._state[k][:n] = self._state[k][old_idx]
            self._steps[:n] = self._steps[old_idx]
            self._shows[:n] = self._shows[old_idx]
            return dropped

    def state_dict(self):
        with self._lock:
            ids = np.fromiter(self._slot.keys(), np.int64, len(self._slot))
            idx = np.fromiter(self._slot.values(), np.int64, len(self._slot))
            out = {"ids": ids, "rows": self._rows[idx].copy(),
                   "steps": self._steps[idx].copy(),
                   "shows": self._shows[idx].copy()}
            for k, st in self._state.items():
                out[k] = st[idx].copy()
            return out

    def load_state_dict(self, d):
        # validate BEFORE mutating (ptps.cpp checks fdim/fopt the same
        # way): a mismatched checkpoint must raise cleanly, not leave a
        # half-restored table with fresh-materialized ids and stale
        # optimizer state
        rows = np.asarray(d["rows"])
        if rows.ndim != 2 or rows.shape[1] != self.dim:
            raise ValueError(
                f"checkpoint rows {rows.shape} do not match table "
                f"dim={self.dim}")
        missing = [k for k in self._state if k not in d]
        if missing:
            raise ValueError(
                f"checkpoint lacks {missing} state for the "
                f"{self.optimizer!r} optimizer — saved by a different "
                "optimizer?")
        if len(rows) != len(np.asarray(d["ids"])):
            raise ValueError("checkpoint ids/rows length mismatch")
        with self._lock:
            idx = self._ensure([int(i) for i in d["ids"]])
            self._rows[idx] = d["rows"]
            self._steps[idx] = d.get("steps", 0)
            if "shows" in d:
                self._shows[idx] = d["shows"]
            for k in self._state:
                self._state[k][idx] = d[k]

    def save(self, path):
        """Atomic checkpoint of this shard (same tmp+rename guarantee
        as utils/checkpoint.py — a crash mid-write never corrupts the
        previous checkpoint). Reference: the_one_ps table save paths."""
        d = self.state_dict()
        # pid+tid+counter: two concurrent SAVE RPCs for the same path
        # (separate handler threads, one process) must not interleave
        # writes into one tmp file and rename the mix over a good ckpt
        with _save_seq_lock:
            global _save_seq
            _save_seq += 1
            seq = _save_seq
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}.{seq}"
        with open(tmp, "wb") as f:
            np.savez(f, **d)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load(self, path):
        with np.load(path) as d:
            self.load_state_dict({k: d[k] for k in d.files})


# ---------------------------------------------------------------------------
# wire protocol: | op u8 | table u16 | n u32 | dim u32 | ids | f32 payload |
# ---------------------------------------------------------------------------

_HDR = struct.Struct("<BHII")
_OP_PULL, _OP_PUSH, _OP_LEN, _OP_STOP = 1, 2, 3, 4
# SAVE/LOAD carry a server-side filesystem path as a raw utf-8 body
# (n = dim = 0) — checkpoint/restore is triggered by the trainer but
# executed where the table lives (reference: the_one_ps save/load)
_OP_SAVE, _OP_LOAD = 5, 6
_MAX_PATH = 4096


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _send_msg(sock, op, table, ids=None, payload=None):
    ids = np.asarray(ids if ids is not None else [], np.int64)
    pay = np.asarray(payload if payload is not None else [], np.float32)
    dim = pay.shape[1] if pay.ndim == 2 else 0
    body = ids.tobytes() + pay.tobytes()
    sock.sendall(_HDR.pack(op, table, len(ids), dim)
                 + struct.pack("<I", len(body)) + body)


def _send_raw(sock, op, table, data: bytes):
    """SAVE/LOAD frames: raw body, n = dim = 0."""
    sock.sendall(_HDR.pack(op, table, 0, 0)
                 + struct.pack("<I", len(data)) + data)


_MAX_BODY = 1 << 30


def _recv_msg(sock, server_side=False):
    op, table, n, dim = _HDR.unpack(_recv_exact(sock, _HDR.size))
    (blen,) = struct.unpack("<I", _recv_exact(sock, 4))
    # strict validation mirroring ptps.cpp's handle_conn: a malformed
    # frame must read as a clean protocol error (the handler drops the
    # connection), not an np ValueError escaping a handler thread nor a
    # 4 GiB allocation from a garbage length field — cap BEFORE reading
    if blen > _MAX_BODY:
        raise ConnectionError(f"ps wire: body {blen}B exceeds cap")
    if op in (_OP_SAVE, _OP_LOAD):
        if n or dim or blen > _MAX_PATH:
            raise ConnectionError("ps wire: malformed save/load frame")
        return op, table, _recv_exact(sock, blen), None
    if blen < 8 * n:
        raise ConnectionError(
            f"ps wire: body {blen}B shorter than {n} ids")
    pay_bytes = blen - 8 * n
    if pay_bytes % 4 or (dim and (pay_bytes // 4) % dim):
        raise ConnectionError(
            f"ps wire: payload {pay_bytes}B not a (n, dim={dim}) "
            "float32 matrix")
    if op == _OP_PUSH and pay_bytes != 4 * n * dim:
        # a PUSH with fewer grad rows than ids would otherwise
        # broadcast one row across all n table rows in push() —
        # silent corruption; the C++ tier rejects this exact frame
        raise ConnectionError(
            f"ps wire: push payload {pay_bytes}B != {n} x dim={dim} "
            "float32 rows")
    if server_side and op in (_OP_PULL, _OP_LEN, _OP_STOP) and pay_bytes:
        # request frames for these ops carry no payload (the C++ tier
        # enforces blen == ids_bytes); the flag exists because CLIENT
        # sides of the same ops DO see payloads in responses
        raise ConnectionError(
            f"ps wire: op {op} request with {pay_bytes}B payload")
    body = _recv_exact(sock, blen)
    ids = np.frombuffer(body[:8 * n], np.int64)
    pay = np.frombuffer(body[8 * n:], np.float32)
    if dim:
        pay = pay.reshape(-1, dim)
    return op, table, ids, pay


class _PSHandler(socketserver.BaseRequestHandler):
    def handle(self):
        server = self.server.ps            # EmbeddingPSServer
        sock = self.request
        try:
            while True:
                op, table, ids, pay = _recv_msg(sock, server_side=True)
                if op == _OP_PULL:
                    rows = server.tables[table].pull(ids)
                    _send_msg(sock, _OP_PULL, table, payload=rows)
                elif op == _OP_PUSH:
                    server.tables[table].push(ids, pay)
                    _send_msg(sock, _OP_PUSH, table)
                elif op == _OP_LEN:
                    n = len(server.tables[table])
                    _send_msg(sock, _OP_LEN, table,
                              ids=np.asarray([n], np.int64))
                elif op == _OP_SAVE:
                    server.tables[table].save(server.wire_ckpt_path(ids))
                    _send_msg(sock, _OP_SAVE, table)
                elif op == _OP_LOAD:
                    server.tables[table].load(server.wire_ckpt_path(ids))
                    _send_msg(sock, _OP_LOAD, table)
                elif op == _OP_STOP:
                    _send_msg(sock, _OP_STOP, table)
                    self.server.shutdown_requested = True
                    # shutdown() must come from another thread
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return
        except (ValueError, IndexError) as e:
            # dim-mismatched push vs the served table, or a table id the
            # server doesn't host: drop the connection cleanly (the C++
            # tier validates against t.dim and breaks the same way)
            print(f"ps server: protocol error, dropping connection: {e}",
                  file=sys.stderr)
        except (ConnectionError, OSError):
            return


class EmbeddingPSServer:
    """One PS process: owns the local shard of every sparse table and
    serves PULL/PUSH over TCP (threaded; SparseTable locks make
    concurrent worker pushes the reference's async-SGD)."""

    def __init__(self, tables, host="127.0.0.1", port=0, ckpt_dir=None):
        self.tables = list(tables)
        # wire SAVE/LOAD write/read server-side files; confine them —
        # the unauthenticated protocol must not hand network peers an
        # arbitrary-file-write primitive. Loopback-bound servers accept
        # any path (only local processes can reach them); non-loopback
        # servers require ckpt_dir (PT_PS_CKPT_DIR via init_server) and
        # reject paths outside it.
        self._loopback = str(host).startswith("127.") or host == "localhost"
        self._ckpt_dir = os.path.realpath(ckpt_dir) if ckpt_dir else None
        srv = socketserver.ThreadingTCPServer((host, port), _PSHandler,
                                              bind_and_activate=False)
        srv.daemon_threads = True
        srv.allow_reuse_address = True
        srv.server_bind()
        srv.server_activate()
        srv.ps = self
        srv.shutdown_requested = False
        self._srv = srv
        self.endpoint = "%s:%d" % srv.server_address

    def wire_ckpt_path(self, raw: bytes):
        """Validate a SAVE/LOAD path from the wire; raises
        ConnectionError (handler drops the connection) when the path is
        not permitted under this server's confinement rule."""
        path = raw.decode()
        if self._ckpt_dir is not None:
            real = os.path.realpath(path)
            if not real.startswith(self._ckpt_dir + os.sep):
                raise ConnectionError(
                    f"ps wire: ckpt path {path!r} outside ckpt_dir")
            return real
        if not self._loopback:
            raise ConnectionError(
                "ps wire: SAVE/LOAD needs ckpt_dir on a non-loopback "
                "server (set PT_PS_CKPT_DIR)")
        return path

    def serve_forever(self):
        self._srv.serve_forever(poll_interval=0.05)

    def serve_in_thread(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


_PTPS = None


def _load_ptps():
    """ctypes binding for the native PS shard (csrc/ptps.cpp; builds
    lazily like the other csrc libraries)."""
    global _PTPS
    if _PTPS is not None:
        return _PTPS
    import ctypes
    import subprocess
    csrc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "csrc")
    so = os.path.join(csrc, "libptps.so")
    # run make unconditionally: the rule depends on ptps.cpp, so a
    # fresh .so is a no-op while a stale one (older ABI, missing
    # symbols) gets rebuilt instead of crashing symbol resolution
    subprocess.run(["make", "-C", csrc, "libptps.so"], check=True,
                   capture_output=True)
    lib = ctypes.CDLL(so)
    lib.ptps_create.restype = ctypes.c_void_p
    lib.ptps_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_float,
                                ctypes.c_longlong, ctypes.c_float,
                                ctypes.c_float, ctypes.c_float,
                                ctypes.c_float]
    lib.ptps_serve.restype = ctypes.c_int
    lib.ptps_serve.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int]
    lib.ptps_size.restype = ctypes.c_longlong
    lib.ptps_size.argtypes = [ctypes.c_void_p]
    lib.ptps_save.restype = ctypes.c_int
    lib.ptps_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_set_ckpt_root.restype = None
    lib.ptps_set_ckpt_root.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_load.restype = ctypes.c_int
    lib.ptps_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_stopping.restype = ctypes.c_int
    lib.ptps_stopping.argtypes = [ctypes.c_void_p]
    lib.ptps_stop.argtypes = [ctypes.c_void_p]
    lib.ptps_destroy.argtypes = [ctypes.c_void_p]
    _PTPS = lib
    return lib


_CPP_OPT = {"sgd": 0, "adagrad": 1, "adam": 2}


class CppPSServer:
    """Native PS shard (csrc/ptps.cpp — the C++ tier the reference's
    BRPC services occupy): one sparse table served over the SAME wire
    protocol as EmbeddingPSServer, so PSClient/_RemoteShard work
    unchanged against either backend. Row init is deterministic per
    (seed, id) but its stream differs from the numpy backend — a table
    lives its whole life on one backend."""

    def __init__(self, dim, optimizer="adagrad", lr=0.01, seed=0,
                 init_scale=0.01, beta1=0.9, beta2=0.999, eps=1e-8,
                 port=0, host="127.0.0.1", ckpt_dir=None):
        if optimizer not in _CPP_OPT:
            raise ValueError(f"unknown sparse optimizer: {optimizer!r}")
        lib = _load_ptps()
        self._lib = lib
        self._h_lock = threading.Lock()
        self._h = lib.ptps_create(int(dim), _CPP_OPT[optimizer],
                                  float(lr), int(seed), float(init_scale),
                                  float(beta1), float(beta2), float(eps))
        # host="" binds all interfaces — only do that when remote
        # workers must dial in (trusted network; docs/distributed.md).
        # ptps_serve only parses dotted-quad, so resolve DNS names here
        # (the python backend accepts them via socketserver)
        if host and not host.replace(".", "").isdigit():
            host = socket.gethostbyname(host)
        bound = lib.ptps_serve(self._h, (host or "").encode(), int(port))
        if bound < 0:
            lib.ptps_destroy(self._h)
            self._h = None
            raise OSError("libptps: could not bind a listening socket")
        if ckpt_dir:
            lib.ptps_set_ckpt_root(
                self._h, os.path.realpath(ckpt_dir).encode())
        self.endpoint = f"{host or '127.0.0.1'}:{bound}"

    def _handle(self):
        if self._h is None:
            raise RuntimeError("CppPSServer is closed")
        return self._h

    def __len__(self):
        with self._h_lock:
            return int(self._lib.ptps_size(self._handle()))

    def serve_in_thread(self):
        """API parity with EmbeddingPSServer: the native accept loop is
        already running in its own thread."""
        self._handle()
        return None

    def save(self, path):
        """Atomic checkpoint in the native PTPS1 format (NOT the
        python .npz — a table lives its whole life on one backend)."""
        with self._h_lock:
            if self._lib.ptps_save(self._handle(), str(path).encode()):
                raise OSError(f"libptps: save to {path!r} failed")

    def load(self, path):
        with self._h_lock:
            if self._lib.ptps_load(self._handle(), str(path).encode()):
                raise OSError(f"libptps: load from {path!r} failed")

    def serve_forever(self):
        """Block until a client sends STOP — or another thread calls
        close(). Each poll snapshots the handle AND calls into the
        native lib under _h_lock: the check-then-call would otherwise
        race a concurrent close() ptps_destroy-ing the handle between
        the two (the old pattern only narrowed that window)."""
        import time
        self._handle()
        while True:
            with self._h_lock:
                if self._h is None or self._lib.ptps_stopping(self._h):
                    return
            time.sleep(0.05)

    def close(self):
        with self._h_lock:
            if self._h is not None:
                self._lib.ptps_destroy(self._h)
                self._h = None


class _RemoteShard:
    """Client-side stub with the SparseTable pull/push surface."""

    def __init__(self, endpoint, table_id):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._table = table_id
        self._lock = threading.Lock()

    def _rpc(self, op, ids=None, payload=None):
        with self._lock:
            _send_msg(self._sock, op, self._table, ids, payload)
            return _recv_msg(self._sock)

    def pull(self, ids):
        _, _, _, rows = self._rpc(_OP_PULL, ids=ids)
        return rows

    def push(self, ids, grads):
        self._rpc(_OP_PUSH, ids=ids, payload=grads)

    def save(self, path):
        """Server-side checkpoint of this shard to `path` (a path on
        the SERVER's filesystem — multi-host deployments point it at
        shared storage)."""
        with self._lock:
            _send_raw(self._sock, _OP_SAVE, self._table, path.encode())
            _recv_msg(self._sock)

    def load(self, path):
        with self._lock:
            _send_raw(self._sock, _OP_LOAD, self._table, path.encode())
            _recv_msg(self._sock)

    def __len__(self):
        _, _, ids, _ = self._rpc(_OP_LEN)
        return int(ids[0])

    def stop_server(self):
        try:
            self._rpc(_OP_STOP)
        except ConnectionError:
            pass

    def close(self):
        self._sock.close()


class PSClient:
    """Worker-side view of one sharded table: routes pull/push by
    id % n_shards and reassembles rows in request order.

    shards: list of SparseTable (in-process) or _RemoteShard stubs —
    the routing math is identical, so single-host training and tests
    exercise the same code the socket deployment runs.
    """

    def __init__(self, shards, async_push=False, max_inflight=64):
        self.shards = list(shards)
        # shard RPCs are independent — issue them concurrently so a
        # lookup pays one network round trip, not n_shards serialized
        # ones (each _RemoteShard already serializes on its own socket)
        self._pool = (ThreadPoolExecutor(max_workers=len(self.shards))
                      if len(self.shards) > 1 or async_push else None)
        # async_push (reference: the async update mode of the PS
        # runtime — trainers don't wait for the push ack): push()
        # returns once the RPCs are QUEUED; flush() drains. Bounded so
        # a fast trainer can't build an unbounded grad backlog. Two
        # staleness/ordering caveats, both inherent to async-SGD: pulls
        # of just-pushed ids may observe pre-update rows, and queued
        # pushes to the SAME shard may apply out of submission order
        # (exactly commutative for sgd's sum; a reordering for
        # adagrad/adam, whose async application is nondeterministic in
        # the reference too).
        self._async = bool(async_push)
        self._inflight = []
        self._max_inflight = int(max_inflight)

    @property
    def n_shards(self):
        return len(self.shards)

    def _fanout(self, fn, per_shard):
        """[(s, args)] -> {s: fn(shard_s, *args)}, concurrently."""
        if self._pool is None:
            return {s: fn(self.shards[s], *a) for s, a in per_shard}
        futs = {s: self._pool.submit(fn, self.shards[s], *a)
                for s, a in per_shard}
        return {s: f.result() for s, f in futs.items()}

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        owner = shard_of(ids, self.n_shards)
        # global ids go to the shard unchanged (tables are keyed hash
        # maps): row init stays a function of (seed, global id) alone,
        # so re-sharding to a different server count reproduces every
        # untouched row
        sels = {s: np.nonzero(owner == s)[0] for s in range(self.n_shards)}
        got = self._fanout(lambda sh, sel: sh.pull(ids[sel]),
                           [(s, (sel,)) for s, sel in sels.items()
                            if len(sel)])
        rows = None
        for s, g in got.items():
            if rows is None:
                rows = np.empty((len(ids), g.shape[1]), np.float32)
            rows[sels[s]] = g
        return rows if rows is not None else np.empty((0, 0), np.float32)

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32)
        owner = shard_of(ids, self.n_shards)
        per_shard = [(s, (np.nonzero(owner == s)[0],))
                     for s in range(self.n_shards) if np.any(owner == s)]
        if self._async:
            while len(self._inflight) >= self._max_inflight:
                self._inflight.pop(0).result()
            # slice AND copy now: the worker thread must not read the
            # caller's arrays later — a trainer reusing a preallocated
            # grad buffer would otherwise push the NEXT step's values
            self._inflight.extend(
                self._pool.submit(
                    lambda sh, i, g: sh.push(i, g),
                    self.shards[s], ids[a[0]].copy(), grads[a[0]].copy())
                for s, a in per_shard)
            return
        self._fanout(lambda sh, sel: sh.push(ids[sel], grads[sel]),
                     per_shard)

    def flush(self):
        """Drain async pushes; re-raises the first shard error."""
        pending, self._inflight = self._inflight, []
        for f in pending:
            f.result()

    def save(self, dirpath):
        """Checkpoint every shard (shard{i}.npz under dirpath). Local
        SparseTables write from this process; remote shards write
        server-side — multi-host deployments need dirpath on shared
        storage. Atomic per shard (tmp+rename)."""
        self.flush()
        os.makedirs(dirpath, exist_ok=True)
        self._fanout(
            lambda sh, p: sh.save(p),
            [(s, (os.path.join(dirpath, f"shard{s}.npz"),))
             for s in range(self.n_shards)])

    def load(self, dirpath):
        # drain queued async pushes FIRST: a stale push applied after
        # its shard's restore would silently overwrite checkpoint rows
        self.flush()
        self._fanout(
            lambda sh, p: sh.load(p),
            [(s, (os.path.join(dirpath, f"shard{s}.npz"),))
             for s in range(self.n_shards)])

    def __len__(self):
        return sum(len(s) for s in self.shards)


# ---------------------------------------------------------------------------
# worker-side layer + step wrapper
# ---------------------------------------------------------------------------


class DistributedEmbedding:
    """Host-RAM sparse embedding fronting a jitted device step.

    lookup(ids) uniques the batch ids, PULLs rows once per unique id,
    and returns (unique_rows, inverse) — feed both to the jitted step,
    gather rows[inverse] INSIDE jit (cheap device gather), and return
    the grad wrt unique_rows as a step output for apply_grads().

    reference: paddle.distributed.ps DistributedEmbedding /
    paddle.static.nn.sparse_embedding (the_one_ps.py pull/push flow).
    """

    def __init__(self, client, dim):
        self.client = client
        self.dim = dim

    def lookup(self, ids):
        ids = np.asarray(ids, np.int64)
        uniq, inv = np.unique(ids.ravel(), return_inverse=True)
        rows = self.client.pull(uniq)
        return rows, inv.reshape(ids.shape).astype(np.int32), uniq

    def apply_grads(self, uniq, grad_rows):
        self.client.push(uniq, np.asarray(grad_rows, np.float32))


def sparse_embedding_step(loss_fn):
    """Wrap loss_fn(rows_gathered, *args) -> loss into a step taking
    (unique_rows, inverse, *args) and returning (loss, grad_unique_rows)
    — the pieces DistributedEmbedding needs around a jitted call. The
    returned fn is jit-compatible (inverse is a static-shape int array).
    """
    import jax

    def step(rows, inv, *args):
        def f(r):
            return loss_fn(r[inv], *args)
        loss, g = jax.value_and_grad(f)(rows)
        return loss, g

    return step


# ---------------------------------------------------------------------------
# role runtime (API parity: paddle.distributed.fleet PS entry points)
# ---------------------------------------------------------------------------

_runtime = {}


def _endpoints():
    eps = os.environ.get("PT_PS_ENDPOINTS", "")
    return [e for e in eps.split(",") if e]


def init_server(tables=None, port=None, host=None, backend=None):
    """Start this process's PS shard. tables: list of SparseTable (or
    (dim, optimizer, lr) tuples); host/port: bind address (default:
    parsed from PT_PS_ENDPOINTS[PT_PS_RANK], else loopback+ephemeral).

    backend (default: the PT_PS_BACKEND env, else "python"): "cpp"
    serves the shard from libptps (csrc/ptps.cpp) — same wire protocol,
    native table + optimizer. The C++ backend hosts ONE table per
    server built from the first table's (dim, optimizer, lr, seed)
    spec and rejects frames addressed to any other table id.

    Workers on OTHER hosts must be able to reach the advertised
    endpoint, so when one is configured the python server binds all
    interfaces (the endpoint's host names how clients dial in, not
    necessarily a local interface name — e.g. a load-balanced DNS
    name)."""
    tabs = []
    for t in (tables or [SparseTable(8)]):
        tabs.append(t if isinstance(t, SparseTable) else SparseTable(*t))
    if port is None:
        eps, rank = _endpoints(), int(os.environ.get("PT_PS_RANK", "0"))
        port = int(eps[rank].rsplit(":", 1)[1]) if eps else 0
        if host is None and eps:
            host = "0.0.0.0"
    backend = backend or os.environ.get("PT_PS_BACKEND", "python")
    if backend == "cpp":
        if len(tabs) != 1:
            raise ValueError(
                "backend='cpp' hosts one table per server process — "
                f"got {len(tabs)}; multi-table workers "
                "(init_worker(n_tables>1)) need the python backend — "
                "every endpoint must serve every table id")
        t = tabs[0]
        if len(t):
            raise ValueError(
                "backend='cpp' cannot adopt rows already materialized "
                "in a python SparseTable — pass a fresh table spec")
        srv = CppPSServer(t.dim, optimizer=t.optimizer, lr=t.lr,
                          seed=t.seed, init_scale=t.init_scale,
                          beta1=t.beta1, beta2=t.beta2, eps=t.eps,
                          port=port, host=host or "127.0.0.1",
                          ckpt_dir=os.environ.get("PT_PS_CKPT_DIR"))
    elif backend == "python":
        srv = EmbeddingPSServer(tabs, host=host or "127.0.0.1", port=port,
                                ckpt_dir=os.environ.get("PT_PS_CKPT_DIR"))
    else:
        raise ValueError(f"unknown PS backend {backend!r}: "
                         "use 'python' or 'cpp'")
    _runtime["server"] = srv
    return srv


def run_server():
    """Blocking serve loop (reference: fleet.run_server)."""
    # NB explicit None check: servers define __len__, so a fresh (empty)
    # server is FALSY and `or` would silently start a second one
    srv = _runtime.get("server")
    if srv is None:
        srv = init_server()
    srv.serve_forever()


def init_worker(n_tables=1):
    """Connect to every endpoint in PT_PS_ENDPOINTS; returns one
    PSClient per table (a single client when n_tables == 1)."""
    eps = _endpoints()
    if not eps:
        raise RuntimeError(
            "init_worker: PT_PS_ENDPOINTS is empty. For single-process "
            "training build PSClient([SparseTable(...)]) directly — the "
            "socket tier is only for multi-process host-RAM tables.")
    clients = [PSClient([_RemoteShard(e, t) for e in eps])
               for t in range(n_tables)]
    _runtime["clients"] = clients
    return clients[0] if n_tables == 1 else clients


def stop_worker(stop_servers=False):
    for c in _runtime.pop("clients", []):
        for s in c.shards:
            if stop_servers:
                s.stop_server()
            s.close()


class TheOnePSRuntime:
    """Role wrapper (reference: the_one_ps.TheOnePSRuntime): PT_PS_ROLE
    in {server, worker} picks the entry point."""

    def __init__(self, tables=None):
        self.tables = tables
        self.role = os.environ.get("PT_PS_ROLE", "worker")

    def run(self):
        if self.role == "server":
            init_server(self.tables)
            run_server()
            return None
        return init_worker()
