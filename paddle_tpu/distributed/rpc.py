"""paddle.distributed.rpc parity: named-worker function RPC.

Reference surface: python/paddle/distributed/rpc/rpc.py (init_rpc /
rpc_sync / rpc_async / get_worker_info / get_all_worker_infos /
get_current_worker_info / shutdown) over a C++ brpc agent plus a
TCPStore rendezvous (rpc.py:86-157) and a store-backed barrier
(rpc.py:268-295).

TPU-native shape: the compute path never needs brpc — SPMD collectives
ride XLA/ICI — so what remains is the *control-plane* job this API
actually does in the reference (driving heterogeneous Python work on
named peers: dataset ingestion, eval loops, PS-adjacent tooling). That
is pure host-side Python, implemented here as a threaded TCP layer:

  * `_TCPStore` — master-hosted key/value rendezvous with blocking
    `get` and atomic `add` (reference core.TCPStore semantics; also the
    barrier primitive, mirroring `_barrier_never_timeout`).
  * `RpcAgent` — per-process server thread executing pickled
    `(fn, args, kwargs)` frames in a thread pool; exceptions pickle
    back and re-raise at the caller (reference PythonFunc/_run_py_func,
    internal.py:18-32).

Like the reference ("Users must use this API in a secure network
environment", rpc.py docstrings) the wire is pickle over a trusted
network — see docs/distributed.md's trusted-network note; the same
assumption covers the PS tier.

Fleet observability rides this wire for free (docs/observability.md):
request frames carry an optional trailing meta dict with the caller's
`trace_id`/`span_id` (the handler executes under that trace context,
so remote flight records and spans join the originating request's
trace), and reply frames carry the server's receive/send wall stamps
`(t1, t2)` — one NTP-style clock sample per round trip, delivered to
`RpcAgent.on_clock_sample`. Both extensions are length-tolerant: old
3-tuple requests and 2-tuple replies still interoperate.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
import traceback
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor

__all__ = [
    "init_rpc",
    "shutdown",
    "rpc_sync",
    "rpc_async",
    "get_worker_info",
    "get_all_worker_infos",
    "get_current_worker_info",
    "RpcFrameError",
]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = -1
_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 30


class RpcFrameError(ConnectionError):
    """A frame over the `_MAX_FRAME` cap, refused on send (before any
    bytes hit the wire — the peer never sees a half-frame) or on recv
    (before allocating the body — a corrupt length prefix fails here,
    not in a giant allocation). Subclasses ConnectionError so existing
    socket-error handling keeps treating it as a dead wire."""


def _resolve_default_timeout(timeout):
    """The reference hardcodes -1 (wait forever) as rpc_sync's default;
    PT_RPC_TIMEOUT_S overrides that default so a hung peer fails in
    bounded time fleet-wide. An EXPLICIT timeout argument always wins —
    only the sentinel consults the env."""
    if timeout is _DEFAULT_RPC_TIMEOUT or timeout == _DEFAULT_RPC_TIMEOUT:
        env = os.environ.get("PT_RPC_TIMEOUT_S", "").strip()
        if env:
            try:
                return float(env)
            except ValueError:
                raise ValueError(
                    f"PT_RPC_TIMEOUT_S={env!r}: want seconds "
                    "(float)") from None
    return timeout


def _routable_ip():
    """The address this host routes external traffic from (no packets
    are sent — UDP connect just resolves the route)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def _recv_exact(sock, n):
    # deliberately duplicates ps_impl's read loop: rpc.py stays
    # stdlib-only (importing ps_impl would pull numpy and the PS tier
    # into every `import paddle_tpu.distributed`)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc wire: peer closed")
        buf += chunk
    return buf


def _send_frame(sock, payload: bytes):
    if len(payload) > _MAX_FRAME:
        raise RpcFrameError(
            f"rpc wire: refusing to send frame of {len(payload)}B — "
            f"exceeds the {_MAX_FRAME}B cap (ship bulk data over a "
            "dedicated channel, e.g. serving/wire.py)")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > _MAX_FRAME:
        raise RpcFrameError(
            f"rpc wire: inbound frame header claims {n}B — exceeds "
            f"the {_MAX_FRAME}B cap (corrupt stream or oversized "
            "sender)")
    return _recv_exact(sock, n)


_tc = None


def _trace_mod():
    """The observability trace-context module, imported lazily so
    `import paddle_tpu.distributed` stays stdlib-cheap; the package is
    stdlib-only at import time, so this can never drag jax in."""
    global _tc
    if _tc is None:
        try:
            from ..observability import trace_context
        except Exception:
            trace_context = False
        _tc = trace_context
    return _tc or None


def _trace_meta():
    """The calling thread's trace context as an rpc meta dict (or
    None). Must run on the CALLER's thread — contextvars do not cross
    the agent's outbound pool."""
    tc = _trace_mod()
    if tc is None:
        return None
    tid = tc.current_trace_id()
    if tid is None:
        return None
    return {"trace_id": tid, "span_id": tc.current_span_id()}


# ---------------------------------------------------------------------------
# rendezvous store


class _TCPStore:
    """Master-hosted key/value store (reference core.TCPStore).

    Ops: SET key val / GET key (blocks until the key exists) / ADD key
    delta (atomic int add, returns the new value). One request per
    connection — rendezvous traffic is a handful of tiny frames, and
    connection-per-op keeps the server loop trivially robust.
    """

    def __init__(self, host, port, is_master, timeout=900.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._srv = None
        if is_master:
            self._data = {}
            self._cv = threading.Condition()
            self._stop = threading.Event()
            self._srv = socket.create_server(
                (host, port), reuse_port=False)
            self._srv.settimeout(0.2)
            self._thread = threading.Thread(
                target=self._serve, name="pt-rpc-store", daemon=True)
            self._thread.start()

    # -- master side --------------------------------------------------
    def _serve(self):
        # thread-per-connection, NOT a bounded pool: GET blocks until
        # the key appears, so at world_size > pool_size every pool
        # thread can be a blocked GET while the unblocking SET sits
        # queued behind them — a rendezvous deadlock. Store traffic is
        # a handful of tiny frames per worker; threads are cheap here.
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()
        self._srv.close()

    def _handle(self, conn):
        try:
            with conn:
                op, key, val = pickle.loads(_recv_frame(conn))
                if op == "set":
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    _send_frame(conn, pickle.dumps(None))
                elif op == "add":
                    with self._cv:
                        new = int(self._data.get(key, 0)) + int(val)
                        self._data[key] = new
                        self._cv.notify_all()
                    _send_frame(conn, pickle.dumps(new))
                elif op == "get":
                    deadline = time.monotonic() + self._timeout
                    with self._cv:
                        while key not in self._data:
                            left = deadline - time.monotonic()
                            if left <= 0 or self._stop.is_set():
                                _send_frame(conn, pickle.dumps(
                                    KeyError(key)))
                                return
                            self._cv.wait(min(left, 0.5))
                        _send_frame(conn, pickle.dumps(self._data[key]))
        except (ConnectionError, OSError, pickle.UnpicklingError):
            pass  # rendezvous peer vanished; its retry/timeout handles it

    def stop(self):
        if self._srv is not None:
            self._stop.set()
            with self._cv:
                self._cv.notify_all()
            self._thread.join(timeout=5)

    # -- client side (works on master too: it dials its own server) ---
    def _request(self, op, key, val=None, timeout=None):
        deadline = time.monotonic() + (
            self._timeout if timeout is None else timeout)
        last = None
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"rpc store: {op} {key!r} timed out") from last
            try:
                s = socket.create_connection(
                    self._addr, timeout=min(left, 5.0))
            except OSError as e:
                # master may not be listening yet during bring-up —
                # retrying an unestablished connection is always safe
                last = e
                time.sleep(0.05)
                continue
            # past this point NOTHING retries: an `add` whose reply is
            # lost after the server applied it would double-increment
            # on re-send (set/get are idempotent; add is not)
            with s:
                s.settimeout(left)
                _send_frame(s, pickle.dumps((op, key, val)))
                out = pickle.loads(_recv_frame(s))
            if isinstance(out, KeyError):
                raise TimeoutError(
                    f"rpc store: key {key!r} never appeared")
            return out

    def set(self, key, val):
        return self._request("set", key, val)

    def get(self, key, timeout=None):
        return self._request("get", key, timeout=timeout)

    def add(self, key, delta):
        return self._request("add", key, delta)


# ---------------------------------------------------------------------------
# agent


class FutureWrapper:
    """Minimal future (reference _FutureWrapper protocol: .wait())."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc = None

    def _finish(self, result=None, exc=None):
        self._result, self._exc = result, exc
        self._done.set()

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("rpc future: no reply within timeout")
        if self._exc is not None:
            raise self._exc
        return self._result


class RpcAgent:
    """One named worker: a server thread executing inbound calls plus a
    client side issuing calls by worker NAME. Instantiable so tests can
    run several agents in one process; the module-level API drives a
    process singleton like the reference agent."""

    def __init__(self, name, rank, world_size, store, barrier=True):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self._store = store
        self._barrier_count = 0
        # clock-sample hook: called as (peer, t_send, t_remote, t_recv,
        # hold_s) after every reply carrying server stamps — the fleet
        # plane points this at its ClockSkewEstimator
        self.on_clock_sample = None
        self._pool = ThreadPoolExecutor(
            max_workers=int(os.environ.get("PT_RPC_THREADS", "8")),
            thread_name_prefix=f"pt-rpc-{name}")
        self._caller = ThreadPoolExecutor(
            max_workers=int(os.environ.get("PT_RPC_THREADS", "8")),
            thread_name_prefix=f"pt-rpc-out-{name}")
        self._stop = threading.Event()
        # inbound calls may arrive while this process is still mid-
        # rendezvous (a peer's barrier only proves RANK 0 finished, not
        # everyone): hold them until the agent is fully wired — for
        # init_rpc, until the module-level _agent is published, so a
        # remote fn calling get_current_worker_info() can't race it
        self._ready = threading.Event()
        host = os.environ.get("PT_RPC_BIND", "127.0.0.1")
        endpoint = os.environ.get("PADDLE_WORKER_ENDPOINT")
        if endpoint:
            host, port = endpoint.rsplit(":", 1)
            self._srv = socket.create_server((host, int(port)))
        else:
            self._srv = socket.create_server((host, 0))
        self._srv.settimeout(0.2)
        ip, port = self._srv.getsockname()[:2]
        if ip in ("0.0.0.0", "::"):
            # a wildcard bind must not be PUBLISHED: peers dialing
            # 0.0.0.0 connect to their own loopback. Advertise the
            # address this host routes out of (UDP connect needs no
            # packets), falling back to the hostname's resolution.
            ip = _routable_ip()
        self._thread = threading.Thread(
            target=self._serve, name=f"pt-rpc-srv-{name}", daemon=True)
        self._thread.start()

        try:
            # rendezvous: publish self, read everyone (reference
            # _set_self_info + _exchange_all_service_infos)
            store.set(f"worker/{rank}",
                      WorkerInfo(name, rank, ip, port))
            infos, seen = [], set()
            for r in range(world_size):
                info = store.get(f"worker/{r}")
                if info.name in seen:
                    raise ValueError(
                        f"rpc: worker name {info.name!r} is not unique")
                seen.add(info.name)
                infos.append(WorkerInfo(*info))
            self._infos = {i.name: i for i in infos}
            if barrier:
                self._ready.set()
                # all servers up before anyone issues a call
                self.barrier()
        except BaseException:
            # a half-built agent must not hold its port/threads — a
            # same-process retry would die with EADDRINUSE
            self.stop()
            raise

    # -- inbound ------------------------------------------------------
    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._pool.submit(self._handle, conn)
        self._srv.close()

    def _handle(self, conn):
        try:
            with conn:
                if not self._ready.wait(timeout=900):
                    # the gate never opened (init failed or wedged):
                    # refuse the call instead of executing against a
                    # half-initialized agent
                    _send_frame(conn, pickle.dumps(
                        ("exc", RuntimeError(
                            f"rpc: agent {self.name!r} not ready within "
                            "900s; refusing inbound call"))))
                    return
                req = pickle.loads(_recv_frame(conn))
                fn, args, kwargs = req[0], req[1], req[2]
                meta = req[3] if len(req) > 3 else None
                t1 = time.time()   # server receipt (NTP-style sample)
                tc = _trace_mod() if meta and meta.get("trace_id") \
                    else None
                try:
                    if tc is not None:
                        with tc.bind(meta["trace_id"],
                                     parent_span=meta.get("span_id")):
                            value = fn(*args, **kwargs)
                    else:
                        value = fn(*args, **kwargs)
                    out = ("ok", value, t1, time.time())
                except Exception as e:  # noqa: BLE001 — ships to caller
                    e._rpc_remote_traceback = traceback.format_exc()
                    out = ("exc", e, t1, time.time())
                try:
                    payload = pickle.dumps(out)
                except Exception as e:  # unpicklable result/exception
                    payload = pickle.dumps(
                        ("exc", RuntimeError(
                            f"rpc: result not picklable: {e}"),
                         t1, time.time()))
                _send_frame(conn, payload)
        except (ConnectionError, OSError, pickle.UnpicklingError):
            pass  # caller vanished or garbage frame; nothing to answer

    # -- outbound -----------------------------------------------------
    def _note_clock(self, to, t_send, t1, t2, t_recv):
        cb = self.on_clock_sample
        if cb is None:
            return
        try:
            cb(to, t_send, (float(t1) + float(t2)) / 2.0, t_recv,
               max(float(t2) - float(t1), 0.0))
        except Exception:
            pass  # a broken estimator must never fail the call itself

    def _call(self, to, fn, args, kwargs, timeout, meta=None):
        info = self._infos.get(to)
        if info is None:
            raise ValueError(f"rpc: unknown worker {to!r}; known: "
                             f"{sorted(self._infos)}")
        payload = pickle.dumps((fn, args or (), kwargs or {}, meta))
        t_send = time.time()
        with socket.create_connection((info.ip, info.port),
                                      timeout=timeout) as s:
            if timeout is not None:
                s.settimeout(timeout)
            _send_frame(s, payload)
            rep = pickle.loads(_recv_frame(s))
        t_recv = time.time()
        status, value = rep[0], rep[1]
        if len(rep) > 3:   # reply carries server stamps (t1, t2)
            self._note_clock(to, t_send, rep[2], rep[3], t_recv)
        if status == "exc":
            remote_tb = getattr(value, "_rpc_remote_traceback", None)
            if remote_tb:
                value.args = (f"{value.args[0] if value.args else ''}"
                              f"\n[remote traceback]\n{remote_tb}",)
            raise value
        return value

    def invoke(self, to, fn, args, kwargs, timeout):
        fut = FutureWrapper()
        eff = None if timeout is None or timeout <= 0 else timeout
        # Trace context rides contextvars, which do NOT cross the
        # _caller pool boundary — capture it here, on the caller's
        # thread, and ship it inside the frame.
        meta = _trace_meta()

        def run():
            try:
                fut._finish(result=self._call(to, fn, args, kwargs, eff,
                                              meta))
            except BaseException as e:  # noqa: BLE001 — raises at wait()
                fut._finish(exc=e)

        self._caller.submit(run)
        return fut

    # -- lifecycle ----------------------------------------------------
    def barrier(self):
        """Store barrier (reference _barrier_never_timeout rpc.py:268):
        master flags first and leaves last so its store outlives every
        waiter."""
        if self.world_size < 2:
            return
        prefix = f"barrier/{self._barrier_count}/"
        self._barrier_count += 1
        if self.rank == 0:
            self._store.add(prefix + "0", 1)
            for r in range(1, self.world_size):
                self._store.get(prefix + str(r))
        else:
            self._store.get(prefix + "0")
            self._store.add(prefix + str(self.rank), 1)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=True)
        self._caller.shutdown(wait=True)

    def worker_info(self, name=None):
        if name is None:
            return self._infos[self.name]
        return self._infos[name]

    def all_worker_infos(self):
        return sorted(self._infos.values(), key=lambda i: i.rank)


_agent = None


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference rpc.py:86 init_rpc — TCPStore rendezvous at the master,
    WorkerInfo exchange, start server, barrier until every peer is up."""
    global _agent
    if _agent is not None:
        raise RuntimeError("rpc: already initialized; call shutdown() first")
    rank = int(os.environ["PADDLE_TRAINER_ID"]) if rank is None else rank
    world_size = (int(os.environ["PADDLE_TRAINERS_NUM"])
                  if world_size is None else world_size)
    master_endpoint = (master_endpoint
                       or os.environ["PADDLE_MASTER_ENDPOINT"])
    host, port = master_endpoint.rsplit(":", 1)
    timeout = float(os.environ.get("FLAGS_stop_check_timeout", "900"))
    store = _TCPStore(host, int(port), rank == 0, timeout=timeout)
    try:
        # publish the agent BEFORE the all-up barrier: our server
        # thread starts serving during rendezvous, and a fast peer may
        # deliver a call (which resolves module state like
        # get_current_worker_info through _agent) the moment ITS
        # barrier completes — publishing after would race that call
        # into 'init_rpc() has not been called'
        agent = RpcAgent(name, rank, world_size, store, barrier=False)
        _agent = agent
        agent._ready.set()   # inbound handlers may now resolve _agent
        agent.barrier()
    except BaseException:
        _agent = None
        # a failed init must release the master port so a corrected
        # retry in this process doesn't hit EADDRINUSE; the half-built
        # agent must release its port/threads too
        try:
            agent.stop()
        except Exception:   # incl. NameError when RpcAgent() itself threw
            pass
        store.stop()
        raise
    return _agent


def _require_agent():
    if _agent is None:
        raise RuntimeError("rpc: init_rpc() has not been called")
    return _agent


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking call of fn(*args, **kwargs) on worker `to` (rpc.py:160).
    The default timeout is wait-forever (-1) unless PT_RPC_TIMEOUT_S
    sets a fleet-wide bound; an explicit `timeout` always wins."""
    timeout = _resolve_default_timeout(timeout)
    return _require_agent().invoke(to, fn, args, kwargs, timeout).wait()


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Non-blocking variant returning a future with .wait() (rpc.py:206).
    Same PT_RPC_TIMEOUT_S default resolution as rpc_sync."""
    timeout = _resolve_default_timeout(timeout)
    return _require_agent().invoke(to, fn, args, kwargs, timeout)


def get_worker_info(name):
    return _require_agent().worker_info(name)


def get_all_worker_infos():
    return _require_agent().all_worker_infos()


def get_current_worker_info():
    return _require_agent().worker_info()


def shutdown():
    """Barrier (all outstanding work done everywhere), stop the server,
    destroy the agent (rpc.py:316). Master's store stops last.

    The agent stays PUBLISHED through the barrier: a fast rank reaches
    shutdown while slower peers are still issuing calls, and those
    inbound calls may resolve module state (get_current_worker_info) —
    un-publishing first made them fail with 'init_rpc() has not been
    called' under load (the start-side twin of this race is handled by
    the _ready gate)."""
    global _agent
    if _agent is None:
        return
    agent = _agent
    agent.barrier()          # every peer is done issuing work
    _agent = None
    agent.stop()
    if agent.rank == 0:
        agent._store.stop()
