"""group_sharded_parallel — ZeRO stages (reference: python/paddle/
distributed/sharding/group_sharded.py).

TPU-native: ZeRO is a sharding-spec choice, not a runtime system —
stage 1/2 shard optimizer slots over dp; stage 3 shards params (GSPMD
all-gathers on use / reduce-scatters grads). The Trainer consumes the
stage; this wrapper keeps paddle's API.
"""
from __future__ import annotations


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level, 2)
    optimizer._sharding_stage = stage
    model._sharding_stage = stage
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save
    save(model.state_dict(), output + ".pdmodel.state")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt.state")
