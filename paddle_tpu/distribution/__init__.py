"""Probability distributions (reference: python/paddle/distribution/*).

Sampling uses explicit PRNG keys; log_prob/entropy are pure jnp.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .._core.state import prng
from .._core.tensor import Tensor, apply, unwrap


def _t(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_t(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _shape(self, shape):
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        return tuple(int(s) for s in shape)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        sh = self._shape(shape) + self._batch_shape
        z = jax.random.normal(prng.next_key(), sh, jnp.float32)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _t(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) -
                      jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) +
                      jnp.zeros(self._batch_shape))

    def cdf(self, value):
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (_t(value) - self.loc) / (self.scale * math.sqrt(2)))))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(np.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        sh = self._shape(shape) + self._batch_shape
        u = jax.random.uniform(prng.next_key(), sh, jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _t(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(self._batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = jnp.log(jnp.maximum(_t(probs), 1e-30))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, -1))

    def sample(self, shape=()):
        sh = self._shape(shape)
        out = jax.random.categorical(prng.next_key(), self.logits, -1,
                                     shape=sh + self._batch_shape)
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, -1)
        v = _t(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(logp, v[..., None], -1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            prng.next_key(), self.probs_, sh).astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(np.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.beta(prng.next_key(), self.alpha, self.beta, sh))

    def log_prob(self, value):
        v = _t(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha) +
                 jax.scipy.special.gammaln(self.beta) -
                 jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v) +
                      (self.beta - 1) * jnp.log1p(-v) - lbeta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(np.broadcast_shapes(self.concentration.shape,
                                             self.rate.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.gamma(prng.next_key(), self.concentration, sh) /
                      self.rate)

    def log_prob(self, value):
        v = _t(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v -
                      jax.scipy.special.gammaln(a))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(prng.next_key(), self.concentration, sh))

    def log_prob(self, value):
        v = _t(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) - \
            jax.scipy.special.gammaln(jnp.sum(a, -1))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale *
                      jax.random.laplace(prng.next_key(), sh))

    def log_prob(self, value):
        return Tensor(-jnp.abs(_t(value) - self.loc) / self.scale -
                      jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale) + jnp.zeros(self._batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        z = jax.random.normal(prng.next_key(), sh)
        return Tensor(jnp.exp(self.loc + self.scale * z))

    def log_prob(self, value):
        v = _t(value)
        logv = jnp.log(v)
        return Tensor(-((logv - self.loc) ** 2) / (2 * self.scale ** 2) -
                      logv - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=()):
        sh = self._shape(shape)
        logits = jnp.log(jnp.maximum(self.probs_, 1e-30))
        draws = jax.random.categorical(
            prng.next_key(), logits, -1,
            shape=(self.total_count,) + sh + self._batch_shape)
        k = self.probs_.shape[-1]
        return Tensor(jnp.sum(jax.nn.one_hot(draws, k), axis=0))

    def log_prob(self, value):
        v = _t(value)
        logp = jnp.log(jnp.maximum(self.probs_, 1e-30))
        coef = (jax.scipy.special.gammaln(jnp.sum(v, -1) + 1) -
                jnp.sum(jax.scipy.special.gammaln(v + 1), -1))
        return Tensor(coef + jnp.sum(v * logp, -1))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(prng.next_key(), sh))

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Geometric(Distribution):
    def __init__(self, probs):
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        u = jax.random.uniform(prng.next_key(), sh, jnp.float32, 1e-7, 1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jnp.log1p(-self.probs_) + jnp.log(self.probs_))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale * jax.random.cauchy(prng.next_key(), sh))

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z ** 2)))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.exponential(prng.next_key(), sh) / self.rate)

    def log_prob(self, value):
        return Tensor(jnp.log(self.rate) - self.rate * _t(value))

    def entropy(self):
        return Tensor(1 - jnp.log(self.rate))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.poisson(prng.next_key(), self.rate, sh)
                      .astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jnp.log(self.rate) - self.rate -
                      jax.scipy.special.gammaln(v + 1))


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) \
            else [transforms]
        super().__init__(base._batch_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        x = value
        ld = 0.0
        for t in reversed(self.transforms):
            xi = t.inverse(x)
            ld = ld + _t(t.forward_log_det_jacobian(xi))
            x = xi
        return Tensor(_t(self.base.log_prob(x)) - ld)


class AffineTransform:
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return Tensor(self.loc + self.scale * _t(x))

    def inverse(self, y):
        return Tensor((_t(y) - self.loc) / self.scale)

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), _t(x).shape))


class ExpTransform:
    def forward(self, x):
        return Tensor(jnp.exp(_t(x)))

    def inverse(self, y):
        return Tensor(jnp.log(_t(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(_t(x))


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        logp = jax.nn.log_softmax(p.logits, -1)
        logq = jax.nn.log_softmax(q.logits, -1)
        return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
        return Tensor(pp * (jnp.log(pp) - jnp.log(qq)) +
                      (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    # generic MC fallback
    x = p.sample((256,))
    return Tensor(jnp.mean(_t(p.log_prob(x)) - _t(q.log_prob(x)), axis=0))
