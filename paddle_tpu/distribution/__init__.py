"""Probability distributions (reference: python/paddle/distribution/*).

Sampling uses explicit PRNG keys; log_prob/entropy are pure jnp.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .._core.state import prng
from .._core.tensor import Tensor, apply, unwrap


def _t(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_t(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _shape(self, shape):
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        return tuple(int(s) for s in shape)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        sh = self._shape(shape) + self._batch_shape
        z = jax.random.normal(prng.next_key(), sh, jnp.float32)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _t(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) -
                      jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) +
                      jnp.zeros(self._batch_shape))

    def cdf(self, value):
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (_t(value) - self.loc) / (self.scale * math.sqrt(2)))))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(np.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        sh = self._shape(shape) + self._batch_shape
        u = jax.random.uniform(prng.next_key(), sh, jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _t(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(self._batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = jnp.log(jnp.maximum(_t(probs), 1e-30))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, -1))

    def sample(self, shape=()):
        sh = self._shape(shape)
        out = jax.random.categorical(prng.next_key(), self.logits, -1,
                                     shape=sh + self._batch_shape)
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, -1)
        v = _t(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(logp, v[..., None], -1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            prng.next_key(), self.probs_, sh).astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(np.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.beta(prng.next_key(), self.alpha, self.beta, sh))

    def log_prob(self, value):
        v = _t(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha) +
                 jax.scipy.special.gammaln(self.beta) -
                 jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v) +
                      (self.beta - 1) * jnp.log1p(-v) - lbeta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(np.broadcast_shapes(self.concentration.shape,
                                             self.rate.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.gamma(prng.next_key(), self.concentration, sh) /
                      self.rate)

    def log_prob(self, value):
        v = _t(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v -
                      jax.scipy.special.gammaln(a))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(prng.next_key(), self.concentration, sh))

    def log_prob(self, value):
        v = _t(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) - \
            jax.scipy.special.gammaln(jnp.sum(a, -1))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale *
                      jax.random.laplace(prng.next_key(), sh))

    def log_prob(self, value):
        return Tensor(-jnp.abs(_t(value) - self.loc) / self.scale -
                      jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale) + jnp.zeros(self._batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        z = jax.random.normal(prng.next_key(), sh)
        return Tensor(jnp.exp(self.loc + self.scale * z))

    def log_prob(self, value):
        v = _t(value)
        logv = jnp.log(v)
        return Tensor(-((logv - self.loc) ** 2) / (2 * self.scale ** 2) -
                      logv - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=()):
        sh = self._shape(shape)
        logits = jnp.log(jnp.maximum(self.probs_, 1e-30))
        draws = jax.random.categorical(
            prng.next_key(), logits, -1,
            shape=(self.total_count,) + sh + self._batch_shape)
        k = self.probs_.shape[-1]
        return Tensor(jnp.sum(jax.nn.one_hot(draws, k), axis=0))

    def log_prob(self, value):
        v = _t(value)
        logp = jnp.log(jnp.maximum(self.probs_, 1e-30))
        coef = (jax.scipy.special.gammaln(jnp.sum(v, -1) + 1) -
                jnp.sum(jax.scipy.special.gammaln(v + 1), -1))
        return Tensor(coef + jnp.sum(v * logp, -1))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(prng.next_key(), sh))

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Geometric(Distribution):
    def __init__(self, probs):
        self.probs_ = _t(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        u = jax.random.uniform(prng.next_key(), sh, jnp.float32, 1e-7, 1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jnp.log1p(-self.probs_) + jnp.log(self.probs_))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(self.loc + self.scale * jax.random.cauchy(prng.next_key(), sh))

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z ** 2)))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.exponential(prng.next_key(), sh) / self.rate)

    def log_prob(self, value):
        return Tensor(jnp.log(self.rate) - self.rate * _t(value))

    def entropy(self):
        return Tensor(1 - jnp.log(self.rate))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        return Tensor(jax.random.poisson(prng.next_key(), self.rate, sh)
                      .astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jnp.log(self.rate) - self.rate -
                      jax.scipy.special.gammaln(v + 1))


class MultivariateNormal(Distribution):
    """reference: python/paddle/distribution/multivariate_normal.py."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 precision_matrix=None, name=None):
        self.loc = _t(loc)
        if scale_tril is not None:
            self._tril = _t(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_t(covariance_matrix))
        elif precision_matrix is not None:
            self._tril = jnp.linalg.cholesky(
                jnp.linalg.inv(_t(precision_matrix)))
        else:
            raise ValueError("need covariance_matrix/scale_tril/"
                             "precision_matrix")
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    def sample(self, shape=()):
        sh = self._shape(shape) + self.loc.shape
        z = jax.random.normal(prng.next_key(), sh, self.loc.dtype)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i", self._tril, z))

    def log_prob(self, value):
        d = self.loc.shape[-1]
        diff = _t(value) - self.loc
        sol = jax.scipy.linalg.solve_triangular(self._tril, diff[..., None],
                                                lower=True)[..., 0]
        m = jnp.sum(sol * sol, -1)
        logdet = 2 * jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1))), -1)
        return Tensor(-0.5 * (m + d * math.log(2 * math.pi) + logdet))

    def entropy(self):
        d = self.loc.shape[-1]
        logdet = 2 * jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1))), -1)
        return Tensor(0.5 * (d * (1 + math.log(2 * math.pi)) + logdet))

    @property
    def mean(self):
        return Tensor(self.loc)


class StudentT(Distribution):
    """reference: python/paddle/distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.df.shape, self.loc.shape,
                                             self.scale.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        z = jax.random.t(prng.next_key(), jnp.broadcast_to(self.df, sh), sh)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = self.df
        y = (_t(value) - self.loc) / self.scale
        return Tensor(gammaln((v + 1) / 2) - gammaln(v / 2) -
                      0.5 * jnp.log(v * math.pi) - jnp.log(self.scale) -
                      (v + 1) / 2 * jnp.log1p(y * y / v))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        return Tensor(jnp.where(self.df > 2,
                                self.scale ** 2 * self.df / (self.df - 2),
                                jnp.nan))


class Chi2(Gamma):
    """reference: python/paddle/distribution/chi2.py — Gamma(df/2, 1/2)."""

    def __init__(self, df, name=None):
        self.df = _t(df)
        super().__init__(self.df / 2.0, 0.5)


class Binomial(Distribution):
    """reference: python/paddle/distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs_ = _t(probs)
        super().__init__(np.broadcast_shapes(self.total_count.shape,
                                             self.probs_.shape))

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        n = jnp.broadcast_to(self.total_count, sh).astype(jnp.float32)
        p = jnp.broadcast_to(self.probs_, sh)
        return Tensor(jax.random.binomial(prng.next_key(), n, p, shape=sh))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        k = _t(value)
        n = self.total_count
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1) +
                      k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))


class ContinuousBernoulli(Distribution):
    """reference: python/paddle/distribution/continuous_bernoulli.py."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_ = _t(probs)
        self._lims = lims
        super().__init__(self.probs_.shape)

    def _log_norm(self):
        p = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.25, p)
        c = jnp.log((2 * jnp.arctanh(1 - 2 * safe)) / (1 - 2 * safe))
        return jnp.where(near_half, math.log(2.0), c)

    def log_prob(self, value):
        v = _t(value)
        p = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p) +
                      self._log_norm())

    def sample(self, shape=()):
        sh = self._shape(shape) + self._batch_shape
        u = jax.random.uniform(prng.next_key(), sh)
        p = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        # inverse CDF
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.25, p)
        num = jnp.log1p(u * (2 * safe - 1) / (1 - safe))
        den = jnp.log(safe / (1 - safe))
        return Tensor(jnp.where(near_half, u, num / den))


class Independent(Distribution):
    """Reinterprets batch dims as event dims (reference:
    python/paddle/distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base._batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base._event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = _t(self.base.log_prob(value))
        return Tensor(jnp.sum(lp, axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        e = _t(self.base.entropy())
        return Tensor(jnp.sum(e, axis=tuple(range(-self.rank, 0))))


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) \
            else [transforms]
        super().__init__(base._batch_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        x = value
        ld = 0.0
        for t in reversed(self.transforms):
            xi = t.inverse(x)
            ld = ld + _t(t.forward_log_det_jacobian(xi))
            x = xi
        return Tensor(_t(self.base.log_prob(x)) - ld)


class AffineTransform:
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return Tensor(self.loc + self.scale * _t(x))

    def inverse(self, y):
        return Tensor((_t(y) - self.loc) / self.scale)

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), _t(x).shape))


class ExpTransform:
    def forward(self, x):
        return Tensor(jnp.exp(_t(x)))

    def inverse(self, y):
        return Tensor(jnp.log(_t(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(_t(x))


class TanhTransform:
    def forward(self, x):
        return Tensor(jnp.tanh(_t(x)))

    def inverse(self, y):
        return Tensor(jnp.arctanh(jnp.clip(_t(y), -1 + 1e-6, 1 - 1e-6)))

    def forward_log_det_jacobian(self, x):
        v = _t(x)
        return Tensor(2.0 * (math.log(2.0) - v - jax.nn.softplus(-2.0 * v)))


class SigmoidTransform:
    def forward(self, x):
        return Tensor(jax.nn.sigmoid(_t(x)))

    def inverse(self, y):
        v = jnp.clip(_t(y), 1e-6, 1 - 1e-6)
        return Tensor(jnp.log(v) - jnp.log1p(-v))

    def forward_log_det_jacobian(self, x):
        v = _t(x)
        return Tensor(-jax.nn.softplus(-v) - jax.nn.softplus(v))


class PowerTransform:
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return Tensor(jnp.power(_t(x), self.power))

    def inverse(self, y):
        return Tensor(jnp.power(_t(y), 1.0 / self.power))

    def forward_log_det_jacobian(self, x):
        v = _t(x)
        return Tensor(jnp.log(jnp.abs(self.power * jnp.power(v,
                                                             self.power - 1))))


class AbsTransform:
    def forward(self, x):
        return Tensor(jnp.abs(_t(x)))

    def inverse(self, y):
        return Tensor(_t(y))  # principal branch

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.zeros_like(_t(x)))


class SoftmaxTransform:
    def forward(self, x):
        return Tensor(jax.nn.softmax(_t(x), -1))

    def inverse(self, y):
        v = jnp.log(jnp.clip(_t(y), 1e-12))
        return Tensor(v - v.mean(-1, keepdims=True))


class StickBreakingTransform:
    """simplex parameterization: R^{K-1} → Δ^K."""

    def forward(self, x):
        v = _t(x)
        k = v.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=v.dtype))
        z = jax.nn.sigmoid(v - offset)
        zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,), z.dtype)],
                               -1)
        cum = jnp.cumprod(1 - z, -1)
        cpad = jnp.concatenate([jnp.ones(z.shape[:-1] + (1,), z.dtype), cum],
                               -1)
        return Tensor(zpad * cpad)

    def inverse(self, y):
        v = _t(y)
        k = v.shape[-1] - 1
        cum = jnp.cumsum(v[..., :-1], -1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros(v.shape[:-1] + (1,), v.dtype), cum[..., :-1]], -1)
        z = jnp.clip(v[..., :-1] / jnp.clip(rest, 1e-12), 1e-12, 1 - 1e-12)
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=v.dtype))
        return Tensor(jnp.log(z) - jnp.log1p(-z) + offset)


class ChainTransform:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        ld = 0.0
        for t in self.transforms:
            ld = ld + _t(t.forward_log_det_jacobian(x))
            x = t.forward(x)
        return Tensor(ld)


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """reference: distribution/kl.py register_kl — decorator registering a
    custom KL(p||q) implementation, dispatched by exact-or-subclass match
    (most-derived pair wins)."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


def _lookup_kl(p, q):
    best, best_score = None, None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            score = (len(type(p).__mro__) - len(cp.__mro__),
                     len(type(q).__mro__) - len(cq.__mro__))
            if best_score is None or score < best_score:
                best, best_score = fn, score
    return best


def kl_divergence(p, q):
    fn = _lookup_kl(p, q)
    if fn is not None:
        return fn(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        logp = jax.nn.log_softmax(p.logits, -1)
        logq = jax.nn.log_softmax(q.logits, -1)
        return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
        return Tensor(pp * (jnp.log(pp) - jnp.log(qq)) +
                      (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    # generic MC fallback
    x = p.sample((256,))
    return Tensor(jnp.mean(_t(p.log_prob(x)) - _t(q.log_prob(x)), axis=0))


class LKJCholesky(Distribution):
    """LKJ distribution over Cholesky factors of correlation matrices
    (reference: python/paddle/distribution/lkj_cholesky.py:128).

    sample() draws an L with unit-diagonal L@L.T via the onion method
    (each row's radius is Beta-distributed, direction uniform on the
    sphere — one vectorized pass, no data-dependent loops on TPU);
    log_prob() is the standard LKJ density over the diagonal of L.
    """

    def __init__(self, dim=2, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky: dim must be >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError(f"unknown sample_method {sample_method}")
        self.dim = int(dim)
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        super().__init__(tuple(np.shape(unwrap(self.concentration))),
                         (dim, dim))

    def sample(self, shape=()):
        d = self.dim
        conc = unwrap(self.concentration)
        sh = tuple(self._shape(shape)) + self._batch_shape
        key1, key2 = jax.random.split(prng.next_key())
        # per-row Beta radii (onion): row i (1-based below the first) has
        # y_i ~ Beta(i/2, conc + (d - 1 - i)/2)
        i = jnp.arange(1, d, dtype=jnp.float32)
        a = 0.5 * i
        b = conc + 0.5 * (d - 1 - i)
        y = jax.random.beta(key1, a, b, sh + (d - 1,))
        u = jax.random.normal(key2, sh + (d - 1, d - 1))
        # unit directions in the lower triangle of each row
        tril = jnp.tril(jnp.ones((d - 1, d - 1)))
        u = u * tril
        norm = jnp.sqrt(jnp.sum(u * u, -1, keepdims=True))
        dirs = u / jnp.maximum(norm, 1e-20)
        w = jnp.sqrt(y)[..., None] * dirs                  # rows 1..d-1
        diag = jnp.sqrt(1.0 - y)                           # L[i, i]
        L = jnp.zeros(sh + (d, d))
        L = L.at[..., 0, 0].set(1.0)
        L = L.at[..., 1:, :-1].set(w)
        # zero the above-row-diagonal part w may carry, then set diagonals
        L = L * jnp.tril(jnp.ones((d, d)))
        L = L.at[..., jnp.arange(1, d), jnp.arange(1, d)].set(diag)
        return Tensor(L)

    def log_prob(self, value):
        """Standard LKJ(η) density over L: Σ_i c_i·log L_ii − log Z(η)."""
        L = unwrap(_t(value)).astype(jnp.float32)
        d = self.dim
        conc = unwrap(self.concentration).astype(jnp.float32)
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        order = 2.0 * (conc[..., None] - 1.0) + d - jnp.arange(
            2, d + 1, dtype=jnp.float32)
        unnorm = jnp.sum(order * jnp.log(diag), -1)
        # normalization (matches the reference's closed form):
        # log Z = Σ_{k=1}^{d-1} [ log π·k/2 + lgamma(η + (d-1-k)/2)
        #                         − lgamma(η + (d-1)/2) ]
        k = jnp.arange(1, d, dtype=jnp.float32)
        lz = jnp.sum(0.5 * k * jnp.log(jnp.pi) +
                     jax.scipy.special.gammaln(conc[..., None] +
                                               0.5 * (d - 1 - k)) -
                     jax.scipy.special.gammaln(conc[..., None] +
                                               0.5 * (d - 1)), -1)
        return Tensor(unnorm - lz)


class ExponentialFamily(Distribution):
    """reference: distribution/exponential_family.py — base class for
    exponential-family distributions; entropy via the Bregman identity
    H = -<natural_params, E[T(x)]> + log_normalizer + E[log h(x)],
    computed here with jax.grad of the log normalizer (the reference
    differentiates its static graph the same way)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        import builtins
        import jax
        nat = [jnp.asarray(_t(p), jnp.float32)
               for p in self._natural_parameters]
        lognorm = self._log_normalizer(*nat)       # batch-shaped
        # grad of the summed normalizer is per-element for an
        # elementwise-batched log normalizer, so batch shape survives
        grads = jax.grad(lambda *np_: jnp.sum(self._log_normalizer(*np_)),
                         argnums=tuple(range(len(nat))))(*nat)
        ent = -jnp.asarray(self._mean_carrier_measure) + lognorm \
            - builtins.sum(n * g for n, g in zip(nat, grads))
        return Tensor(ent)
