"""FFT ops (reference: python/paddle/fft.py) → jnp.fft (XLA FFT HLO)."""
from __future__ import annotations

import jax.numpy as jnp

from ._core.tensor import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "hfft2",
           "ihfft2", "hfftn", "ihfftn", "fftfreq", "rfftfreq", "fftshift",
           "ifftshift"]


def _norm(norm):
    return norm if norm in ("ortho", "forward") else "backward"


def _mk1(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), x, name=name)
    op.__name__ = name
    return op


fft = _mk1(jnp.fft.fft, "fft")
ifft = _mk1(jnp.fft.ifft, "ifft")
rfft = _mk1(jnp.fft.rfft, "rfft")
irfft = _mk1(jnp.fft.irfft, "irfft")
hfft = _mk1(jnp.fft.hfft, "hfft")
ihfft = _mk1(jnp.fft.ihfft, "ihfft")


def _mk2(jfn, name):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_arg=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x, name=name)
    op.__name__ = name
    return op


fft2 = _mk2(jnp.fft.fft2, "fft2")
ifft2 = _mk2(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2(jnp.fft.irfft2, "irfft2")


def _mkn(jfn, name):
    def op(x, s=None, axes=None, norm="backward", name_arg=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x, name=name)
    op.__name__ = name
    return op


fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.hfft(jnp.fft.ifft(a, axis=axes[0]), axis=axes[1],
                                        norm=_norm(norm)), x, name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.ihfft(a, axis=axes[1], norm=_norm(norm)), x, name="ihfft2")


hfftn = hfft2
ihfftn = ihfft2


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ._core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ._core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x, name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x, name="ifftshift")
