"""Framework glue (reference: python/paddle/framework/__init__.py)."""
from __future__ import annotations

import numpy as np

from .._core import dtypes as _dt
from .._core import state as _state
from .._core.tensor import Tensor, Parameter
from . import random  # noqa: F401
from .io import save, load  # noqa: F401

# paddle.framework.dtype — dtype constructor/alias
dtype = _dt.convert_dtype


def in_dynamic_mode():
    return True


def in_pir_mode():
    return False


def in_dynamic_or_pir_mode():
    return True


def use_pir_api():
    return False


def set_grad_enabled(mode):
    from ..autograd import set_grad_enabled_ctx
    return set_grad_enabled_ctx(mode)


def is_grad_enabled():
    return _state.grad_enabled()


_global_flags = {}


def set_flags(flags):
    _global_flags.update(flags)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _global_flags.get(f) for f in flags}
