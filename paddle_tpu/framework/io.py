"""paddle.save / paddle.load (reference: python/paddle/framework/io.py).

Serialization: numpy-backed pickle for arbitrary nested state
(state_dicts, optimizer state, plain tensors). Sharded/async checkpoint
for training lives in paddle_tpu.utils.checkpoint (orbax-style).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .._core.tensor import Tensor, Parameter


class _TensorPayload:
    __slots__ = ("array", "is_param", "name", "stop_gradient")

    def __init__(self, array, is_param, name, stop_gradient):
        self.array = array
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), isinstance(obj, Parameter),
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    import jax.numpy as jnp
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        arr = jnp.asarray(obj.array)
        t = Parameter(arr, name=obj.name) if obj.is_param else Tensor(arr, name=obj.name)
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = str(path) + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)
    os.replace(tmp, path)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
