"""RNG state management (reference: python/paddle/framework/random.py)."""
from __future__ import annotations

from .._core import state as _state


def get_rng_state(device=None):
    return [_state.get_rng_state()]


def set_rng_state(state_list, device=None):
    st = state_list[0] if isinstance(state_list, (list, tuple)) else state_list
    _state.set_rng_state(st)


def get_cuda_rng_state():
    return [_state.get_rng_state()]


def set_cuda_rng_state(state_list):
    set_rng_state(state_list)
