"""paddle.geometric parity (reference: python/paddle/geometric):
graph message passing via XLA segment ops — send_u_recv / send_ue_recv /
segment reductions map to jax.ops.segment_* (one fused scatter on TPU).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, apply, unwrap

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "sample_neighbors",
           "reindex_graph"]


def _num_segments(dst, out_size):
    if out_size is not None:
        return int(out_size)
    return int(np.asarray(dst).max()) + 1


def _segment(x, ids, num, pool):
    if pool == "sum":
        return jax.ops.segment_sum(x, ids, num)
    if pool == "mean":
        s = jax.ops.segment_sum(x, ids, num)
        c = jax.ops.segment_sum(jnp.ones_like(ids, x.dtype), ids, num)
        return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))
    if pool == "max":
        return jax.ops.segment_max(x, ids, num)
    if pool == "min":
        return jax.ops.segment_min(x, ids, num)
    raise ValueError(pool)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    num = _num_segments(unwrap(dst_index), out_size)

    def fn(a, src, dst):
        msgs = jnp.take(a, src, axis=0)
        return _segment(msgs, dst, num, reduce_op)
    return apply(fn, x, src_index, dst_index, name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    num = _num_segments(unwrap(dst_index), out_size)

    def fn(a, e, src, dst):
        msgs = jnp.take(a, src, axis=0)
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "sub":
            msgs = msgs - e
        elif message_op == "mul":
            msgs = msgs * e
        elif message_op == "div":
            msgs = msgs / e
        return _segment(msgs, dst, num, reduce_op)
    return apply(fn, x, y, src_index, dst_index, name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    def fn(a, b, src, dst):
        u = jnp.take(a, src, axis=0)
        v = jnp.take(b, dst, axis=0)
        return {"add": u + v, "sub": u - v, "mul": u * v,
                "div": u / v}[message_op]
    return apply(fn, x, y, src_index, dst_index, name="send_uv")


def segment_sum(data, segment_ids, name=None):
    num = _num_segments(unwrap(segment_ids), None)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num), data, segment_ids,
                 name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    num = _num_segments(unwrap(segment_ids), None)
    return apply(lambda d, i: _segment(d, i, num, "mean"), data, segment_ids,
                 name="segment_mean")


def segment_max(data, segment_ids, name=None):
    num = _num_segments(unwrap(segment_ids), None)
    return apply(lambda d, i: jax.ops.segment_max(d, i, num), data, segment_ids,
                 name="segment_max")


def segment_min(data, segment_ids, name=None):
    num = _num_segments(unwrap(segment_ids), None)
    return apply(lambda d, i: jax.ops.segment_min(d, i, num), data, segment_ids,
                 name="segment_min")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Host-side uniform neighbor sampling (data-dependent shapes)."""
    r = np.asarray(unwrap(row))
    cp = np.asarray(unwrap(colptr))
    nodes = np.asarray(unwrap(input_nodes))
    out_n, out_count = [], []
    rng = np.random.RandomState(0)
    for v in nodes:
        nbrs = r[cp[v]:cp[v + 1]]
        if sample_size > 0 and len(nbrs) > sample_size:
            nbrs = rng.choice(nbrs, sample_size, replace=False)
        out_n.append(nbrs)
        out_count.append(len(nbrs))
    return (Tensor(jnp.asarray(np.concatenate(out_n) if out_n else
                               np.zeros(0, r.dtype))),
            Tensor(jnp.asarray(np.asarray(out_count, np.int64))))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    xs = np.asarray(unwrap(x))
    nb = np.asarray(unwrap(neighbors))
    uniq, inv = np.unique(np.concatenate([xs, nb]), return_inverse=True)
    # order: keep x first (paddle semantics: x nodes keep ids 0..len(x))
    order = {v: i for i, v in enumerate(xs)}
    nxt = len(xs)
    remap = {}
    for v in np.concatenate([xs, nb]):
        if v not in order and v not in remap:
            remap[v] = nxt
            nxt += 1
    full = {**order, **remap}
    reindexed = np.asarray([full[v] for v in nb], np.int64)
    out_nodes = np.asarray(sorted(full, key=full.get), np.int64)
    return (Tensor(jnp.asarray(reindexed)),
            Tensor(jnp.asarray(out_nodes)),
            Tensor(jnp.asarray(np.asarray(unwrap(count)))))
