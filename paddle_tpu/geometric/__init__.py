"""paddle.geometric parity (reference: python/paddle/geometric):
graph message passing via XLA segment ops — send_u_recv / send_ue_recv /
segment reductions map to jax.ops.segment_* (one fused scatter on TPU).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core import state as _state
from .._core.tensor import Tensor, apply, unwrap

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "sample_neighbors",
           "reindex_graph", "weighted_sample_neighbors", "reindex_heter_graph"]


def _num_segments(dst, out_size):
    if out_size is not None:
        return int(out_size)
    if isinstance(dst, jax.core.Tracer):
        raise ValueError(
            "number of segments is data-dependent; pass out_size= when "
            "calling this op under jit/to_static (XLA needs static shapes)")
    d = np.asarray(dst)
    return int(d.max()) + 1 if d.size else 0


def _segment(x, ids, num, pool):
    if pool == "sum":
        return jax.ops.segment_sum(x, ids, num)
    if pool == "mean":
        s = jax.ops.segment_sum(x, ids, num)
        c = jax.ops.segment_sum(jnp.ones_like(ids, x.dtype), ids, num)
        return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))
    if pool == "max":
        return jax.ops.segment_max(x, ids, num)
    if pool == "min":
        return jax.ops.segment_min(x, ids, num)
    raise ValueError(pool)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    num = _num_segments(unwrap(dst_index), out_size)

    def fn(a, src, dst):
        msgs = jnp.take(a, src, axis=0)
        return _segment(msgs, dst, num, reduce_op)
    return apply(fn, x, src_index, dst_index, name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    num = _num_segments(unwrap(dst_index), out_size)

    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"send_ue_recv: unknown message_op {message_op!r}; "
                         "expected add/sub/mul/div")

    def fn(a, e, src, dst):
        msgs = jnp.take(a, src, axis=0)
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "sub":
            msgs = msgs - e
        elif message_op == "mul":
            msgs = msgs * e
        else:
            msgs = msgs / e
        return _segment(msgs, dst, num, reduce_op)
    return apply(fn, x, y, src_index, dst_index, name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"send_uv: unknown message_op {message_op!r}; "
                         "expected add/sub/mul/div")

    def fn(a, b, src, dst):
        u = jnp.take(a, src, axis=0)
        v = jnp.take(b, dst, axis=0)
        return {"add": u + v, "sub": u - v, "mul": u * v,
                "div": u / v}[message_op]
    return apply(fn, x, y, src_index, dst_index, name="send_uv")


def segment_sum(data, segment_ids, name=None, *, out_size=None):
    num = _num_segments(unwrap(segment_ids), out_size)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num), data, segment_ids,
                 name="segment_sum")


def segment_mean(data, segment_ids, name=None, *, out_size=None):
    num = _num_segments(unwrap(segment_ids), out_size)
    return apply(lambda d, i: _segment(d, i, num, "mean"), data, segment_ids,
                 name="segment_mean")


def segment_max(data, segment_ids, name=None, *, out_size=None):
    num = _num_segments(unwrap(segment_ids), out_size)
    return apply(lambda d, i: jax.ops.segment_max(d, i, num), data, segment_ids,
                 name="segment_max")


def segment_min(data, segment_ids, name=None, *, out_size=None):
    num = _num_segments(unwrap(segment_ids), out_size)
    return apply(lambda d, i: jax.ops.segment_min(d, i, num), data, segment_ids,
                 name="segment_min")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Host-side uniform neighbor sampling (data-dependent shapes)."""
    r = np.asarray(unwrap(row))
    cp = np.asarray(unwrap(colptr))
    nodes = np.asarray(unwrap(input_nodes))
    e = np.asarray(unwrap(eids)) if eids is not None else None
    if return_eids and e is None:
        raise ValueError("sample_neighbors: return_eids=True requires eids")
    out_i, out_count = [], []
    rng = np.random.default_rng(_state.prng.next_np_seed())
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        pick = np.arange(lo, hi)
        if 0 < sample_size < len(pick):
            pick = rng.choice(pick, sample_size, replace=False)
        out_i.append(pick)
        out_count.append(len(pick))
    idx = np.concatenate(out_i) if out_i else np.zeros(0, np.int64)
    res = (Tensor(jnp.asarray(r[idx])),
           Tensor(jnp.asarray(np.asarray(out_count, np.int64))))
    if return_eids:
        res = res + (Tensor(jnp.asarray(e[idx])),)
    return res


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    # paddle semantics: x nodes keep ids 0..len(x)-1; new nodes get ids in
    # first-appearance order within neighbors. Vectorized via searchsorted.
    xs = np.asarray(unwrap(x))
    nb = np.asarray(unwrap(neighbors))
    fresh = nb[~np.isin(nb, xs)]
    uniq, first = np.unique(fresh, return_index=True)
    new_in_order = uniq[np.argsort(first)]
    out_nodes = np.concatenate([xs, new_in_order]).astype(np.int64)
    sort_idx = np.argsort(out_nodes, kind="stable")
    reindexed = sort_idx[np.searchsorted(out_nodes[sort_idx], nb)]
    return (Tensor(jnp.asarray(reindexed.astype(np.int64))),
            Tensor(jnp.asarray(out_nodes)),
            Tensor(jnp.asarray(np.asarray(unwrap(count)))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted neighbor sampling (reference geometric/sampling/
    neighbors.py:218): draw up to sample_size neighbors per node without
    replacement, probability ∝ edge_weight."""
    r = np.asarray(unwrap(row))
    cp = np.asarray(unwrap(colptr))
    w = np.asarray(unwrap(edge_weight), np.float64)
    nodes = np.asarray(unwrap(input_nodes))
    e = np.asarray(unwrap(eids)) if eids is not None else None
    if return_eids and e is None:
        raise ValueError("weighted_sample_neighbors: return_eids=True "
                         "requires eids")
    rng = np.random.default_rng(_state.prng.next_np_seed())
    out_i, out_count = [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        pick = np.arange(lo, hi)
        if 0 < sample_size < len(pick):
            p = w[lo:hi]
            p = p / p.sum() if p.sum() > 0 else None
            pick = rng.choice(pick, sample_size, replace=False, p=p)
        out_i.append(pick)
        out_count.append(len(pick))
    idx = np.concatenate(out_i) if out_i else np.zeros(0, np.int64)
    res = (Tensor(jnp.asarray(r[idx])),
           Tensor(jnp.asarray(np.asarray(out_count, np.int64))))
    if return_eids:
        res = res + (Tensor(jnp.asarray(e[idx])),)
    return res


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference geometric/reindex.py:153):
    same renumbering as reindex_graph, with per-edge-type neighbor/count
    lists sharing ONE id space (first-appearance order across the
    concatenation)."""
    xs = np.asarray(unwrap(x))
    nbs = [np.asarray(unwrap(n)) for n in neighbors]
    cts = [np.asarray(unwrap(c)) for c in count]
    allnb = np.concatenate(nbs) if nbs else np.zeros(0, np.int64)
    fresh = allnb[~np.isin(allnb, xs)]
    uniq, first = np.unique(fresh, return_index=True)
    new_in_order = uniq[np.argsort(first)]
    out_nodes = np.concatenate([xs, new_in_order]).astype(np.int64)
    sort_idx = np.argsort(out_nodes, kind="stable")
    reindexed = sort_idx[np.searchsorted(out_nodes[sort_idx], allnb)]
    return (Tensor(jnp.asarray(reindexed.astype(np.int64))),
            Tensor(jnp.asarray(out_nodes)),
            Tensor(jnp.asarray(np.concatenate(cts) if cts else
                               np.zeros(0, np.int64))))
