"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py).

Counts matmul/conv MACs by hooking layer forwards on a real run.
"""
from __future__ import annotations

import numpy as np

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer


def _conv_flops(layer, inp, out):
    k = int(np.prod(layer._kernel_size))
    cin = layer._in_channels // layer._groups
    out_elems = int(np.prod(out.shape))
    return out_elems * cin * k


def _linear_flops(layer, inp, out):
    return int(np.prod(out.shape)) * layer.weight.shape[0]


def flops(net, input_size, custom_ops=None, print_detail=False):
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd
    from ..tensor.creation import zeros

    total = [0]
    rows = []
    hooks = []

    def make_hook(fn, layer, name):
        def hook(l, i, o):
            if isinstance(o, (tuple, list)):
                o = o[0]
            f = fn(l, i, o)
            total[0] += f
            rows.append((name, f))
        return hook

    for name, layer in net.named_sublayers(include_self=True):
        if isinstance(layer, _ConvNd):
            hooks.append(layer.register_forward_post_hook(
                make_hook(_conv_flops, layer, name)))
        elif isinstance(layer, Linear):
            hooks.append(layer.register_forward_post_hook(
                make_hook(_linear_flops, layer, name)))
        if custom_ops and type(layer) in custom_ops:
            fn = custom_ops[type(layer)]
            hooks.append(layer.register_forward_post_hook(
                make_hook(lambda l, i, o, fn=fn: fn(l, i, o), layer, name)))

    was_training = net.training
    net.eval()
    x = zeros(list(input_size))
    try:
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    if print_detail:
        for name, f in rows:
            print(f"{name:<40}{f / 1e6:>12.2f} MMACs")
    print(f"Total MACs: {total[0] / 1e9:.3f} G "
          f"(≈ {2 * total[0] / 1e9:.3f} GFLOPs)")
    return total[0]
