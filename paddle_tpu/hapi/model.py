"""High-level Model API (reference: python/paddle/hapi/model.py)."""
from __future__ import annotations

import os
import time

import numpy as np

from .._core.tensor import Tensor
from ..io import DataLoader
from .. import callbacks as cb_mod
from ..observability import device_telemetry as _devtel
from ..observability import health as _health
from ..observability.logging import get_logger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])

    def _loss_value(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        loss = self._loss(*outs, *labs)
        if isinstance(loss, (list, tuple)):
            from ..tensor.math import add_n
            loss = add_n([l for l in loss])
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*ins)
        loss = self._loss_value(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = [float(np.asarray(loss._value))]
        for m in self._metrics:
            res = m.update(*_to_metric_args(m, outputs, labels))
            metrics.append(res)
        return metrics if len(metrics) > 1 else metrics[0]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import no_grad
        with no_grad():
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            outputs = self.network(*ins)
            loss = self._loss_value(outputs, labels) if self._loss else None
        metrics = [float(np.asarray(loss._value))] if loss is not None else []
        for m in self._metrics:
            res = m.update(*_to_metric_args(m, outputs, labels))
            metrics.append(res)
        return metrics if len(metrics) > 1 else (metrics[0] if metrics else None)

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad
        with no_grad():
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            out = self.network(*ins)
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle,
            drop_last=drop_last, num_workers=num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbs = cb_mod.config_callbacks(callbacks, model=self, epochs=epochs,
                                      steps=steps, verbose=verbose,
                                      batch_size=batch_size,
                                      metrics=self._metric_names())
        cbs.on_train_begin()
        it = 0
        # MFU window markers: FLOPs issued by tracked/jitted entry
        # points between two log records, over the wall time between
        # them (0.0 for a purely eager network — nothing tracked ran)
        mfu_flops = _devtel.COSTS.issued_totals()["flops"]
        mfu_t = time.perf_counter()
        for epoch in range(epochs):
            self.stop_training = False
            cbs.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, data in enumerate(loader):
                cbs.on_train_batch_begin(step)
                inputs, labels = _split_data(data)
                t0 = time.perf_counter()
                res = self.train_batch(inputs, labels,
                                       update=(it + 1) % accumulate_grad_batches == 0)
                dt = time.perf_counter() - t0
                logs = self._pack_logs(res)
                cbs.on_train_batch_end(step, logs)
                it += 1
                if logs.get("loss") is not None:
                    # free host-side health check (loss is already a
                    # float): a non-finite loss bumps
                    # pt_train_nonfinite_total + the flight recorder
                    _health.note_host_loss(logs["loss"], where="hapi.fit")
                if log_freq and it % log_freq == 0:
                    # structured step record (flight recorder always;
                    # the log stream when PADDLE_TPU_LOG is wired);
                    # memory comes from the device-memory accountant
                    # (allocator stats + live-array walk, peak kept),
                    # MFU from the issued-FLOPs window since the last
                    # record
                    mem = _devtel.ACCOUNTANT.poll(force=True)
                    now = time.perf_counter()
                    flops = _devtel.COSTS.issued_totals()["flops"]
                    mfu = _devtel.COSTS.mfu_over(flops - mfu_flops,
                                                 now - mfu_t)
                    mfu_flops, mfu_t = flops, now
                    get_logger("hapi").event(
                        "train.step", epoch=epoch, step=step, iter=it,
                        loss=logs.get("loss"), step_time_s=dt,
                        samples_per_s=(batch_size / dt) if dt > 0
                        else None,
                        live_device_bytes=mem["live_bytes"],
                        hbm_peak_bytes=mem["live_peak_bytes"],
                        bytes_in_use=mem.get("bytes_in_use"),
                        mfu=mfu,
                        nonfinite_total=_health.HEALTH.nonfinite_steps)
                if num_iters is not None and it >= num_iters:
                    break
            cbs.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=0,
                              num_workers=num_workers)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training or (num_iters is not None and it >= num_iters):
                break
        cbs.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for data in loader:
            inputs, labels = _split_data(data)
            res = self.eval_batch(inputs, labels)
            if res is not None:
                first = res[0] if isinstance(res, list) else res
                total_loss += float(first)
                n += 1
        logs = {}
        if self._loss and n:
            logs["loss"] = total_loss / n
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            logs.update(dict(zip(names, vals)))
        if verbose:
            print("Eval:", {k: round(float(v), 5) for k, v in logs.items()})
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for data in loader:
            inputs, _ = _split_data(data)
            out = self.predict_batch(inputs)
            outputs.append(out)
        if stack_outputs and outputs:
            import jax.numpy as jnp
            firsts = [o if isinstance(o, Tensor) else o[0] for o in outputs]
            return [Tensor(jnp.concatenate([f._value for f in firsts]))]
        return [outputs]

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _pack_logs(self, res):
        names = self._metric_names()
        vals = res if isinstance(res, list) else [res]
        return dict(zip(names, [float(np.mean(v)) if not isinstance(v, list)
                                else float(np.mean(v[0])) for v in vals]))

    def save(self, path, training=True):
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)


def _split_data(data):
    if isinstance(data, (list, tuple)):
        if len(data) >= 2:
            return data[:-1] if len(data) > 2 else [data[0]], data[-1]
        return [data[0]], None
    return [data], None


def _to_metric_args(metric, outputs, labels):
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    labs = labels if isinstance(labels, (list, tuple)) else [labels]
    try:
        pre = metric.compute(*outs, *labs)
        return pre if isinstance(pre, (list, tuple)) else (pre,)
    except Exception:
        return (*outs, *labs)


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter-count summary (reference: python/paddle/hapi/model_summary.py)."""
    rows = []
    total, trainable = 0, 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}"]
    lines.append("-" * (width + 36))
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    lines.append("-" * (width + 36))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    out = "\n".join(lines)
    print(out)
    return {"total_params": total, "trainable_params": trainable}
