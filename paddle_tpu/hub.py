"""paddle.hub parity (offline: local-dir sources only; zero egress)."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local"):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no network)")
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod) if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local"):
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, *args, source="local", **kwargs):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no network)")
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(*args, **kwargs)


def load_state_dict_from_url(url, model_dir=None, check_hash=False,
                             file_name=None, map_location=None):
    """reference: hub.load_state_dict_from_url. Zero-egress build: serve
    from a local cache only — if the file named by the url basename is
    already in model_dir (or PADDLE_HUB_DIR), load it; never download."""
    import os
    from .framework.io import load
    base = file_name or os.path.basename(url.split("?")[0])
    cand_dirs = [d for d in (model_dir, os.environ.get("PADDLE_HUB_DIR"),
                             os.path.expanduser("~/.cache/paddle/hub"))
                 if d]
    for d in cand_dirs:
        p = os.path.join(d, base)
        if os.path.exists(p):
            return load(p)
    raise RuntimeError(
        f"load_state_dict_from_url: no network egress in this build and "
        f"'{base}' was not found in {cand_dirs}; place the file locally "
        f"and retry")
