"""paddle.hub parity (offline: local-dir sources only; zero egress)."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local"):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no network)")
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod) if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local"):
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, *args, source="local", **kwargs):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no network)")
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(*args, **kwargs)
