"""paddle.incubate parity (reference: python/paddle/incubate/*)."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import (  # noqa: F401
    ExponentialMovingAverage, LookAhead, ModelAverage,
)


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..tensor.creation import triu, full_like
    from ..tensor.manipulation import where
    import jax.numpy as jnp
    from .._core.tensor import apply
    def fn(a):
        import jax
        s, k = a.shape[-2], a.shape[-1]
        mask = jnp.tril(jnp.ones((s, k), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)
    return apply(fn, x, name="softmax_mask_fuse_upper_triangle")
