"""paddle.incubate parity (reference: python/paddle/incubate/*)."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import (  # noqa: F401
    ExponentialMovingAverage, LookAhead, ModelAverage,
)


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..tensor.creation import triu, full_like
    from ..tensor.manipulation import where
    import jax.numpy as jnp
    from .._core.tensor import apply
    def fn(a):
        import jax
        s, k = a.shape[-2], a.shape[-1]
        mask = jnp.tril(jnp.ones((s, k), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)
    return apply(fn, x, name="softmax_mask_fuse_upper_triangle")


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate softmax_mask_fuse — softmax(x + mask) in one
    fused XLA graph."""
    import jax
    from .._core.tensor import apply
    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                 name="softmax_mask_fuse")


def identity_loss(x, reduction="none"):
    """reference: incubate identity_loss (IPU-era loss marker)."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


# graph ops: the geometric module IS the implementation (reference moved
# these from incubate to paddle.geometric; both names stay valid)
from ..geometric import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min,
    sample_neighbors as graph_sample_neighbors,
    reindex_graph as graph_reindex,
    send_u_recv as graph_send_recv,
)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference: incubate.graph_khop_sampler — multi-hop neighbor
    sampling; composed from per-hop sample_neighbors + reindex_graph.
    Returns (edge_src, edge_dst, sample_index, reindex_x): edges in the
    RENUMBERED id space, the subgraph's original node ids, and the
    renumbered seed nodes — the reference's 4-tuple contract."""
    import numpy as np
    from ..geometric import sample_neighbors
    from .._core.tensor import Tensor
    import jax.numpy as jnp
    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler: return_eids=True is not implemented "
            "(pass eids through per-hop sample_neighbors if needed)")
    seeds = np.asarray(input_nodes._value if isinstance(input_nodes, Tensor)
                       else input_nodes).astype(np.int64)
    cur = seeds
    edge_src_all, edge_dst_all = [], []
    for size in sample_sizes:
        nbr, cnt = sample_neighbors(row, colptr, Tensor(jnp.asarray(cur)),
                                    sample_size=size)[:2]
        dst = np.repeat(cur, np.asarray(cnt._value))
        edge_src_all.append(np.asarray(nbr._value).astype(np.int64))
        edge_dst_all.append(dst)
        cur = np.unique(np.asarray(nbr._value).astype(np.int64))
    src = np.concatenate(edge_src_all) if edge_src_all else \
        np.zeros(0, np.int64)
    dst = np.concatenate(edge_dst_all) if edge_dst_all else \
        np.zeros(0, np.int64)
    # renumber: seeds keep ids 0..len-1, new nodes by first appearance
    fresh = np.concatenate([src, dst])
    fresh = fresh[~np.isin(fresh, seeds)]
    uniq, first = np.unique(fresh, return_index=True)
    sample_index = np.concatenate([seeds, uniq[np.argsort(first)]])
    sort_idx = np.argsort(sample_index, kind="stable")
    lut_sorted = sample_index[sort_idx]
    remap = lambda a: sort_idx[np.searchsorted(lut_sorted, a)]  # noqa: E731
    return (Tensor(jnp.asarray(remap(src))),
            Tensor(jnp.asarray(remap(dst))),
            Tensor(jnp.asarray(sample_index)),
            Tensor(jnp.asarray(remap(seeds))))


from .. import inference  # noqa: E402,F401  (reference re-exports it)
from . import tensor  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import checkpoint  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import layers  # noqa: E402,F401
from . import operators  # noqa: E402,F401
from . import autotune  # noqa: E402,F401


def __getattr__(name):
    if name == "multiprocessing":
        # LAZY on purpose: importing the module runs init_reductions(),
        # which globally rewires ForkingPickler for Tensors (shm-handle
        # payloads, sender-held blocks). That is the documented OPT-IN
        # contract — `import paddle_tpu.incubate.multiprocessing` —
        # and must not happen on bare `import paddle_tpu`.
        import importlib
        mod = importlib.import_module(__name__ + ".multiprocessing")
        globals()["multiprocessing"] = mod
        return mod
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
