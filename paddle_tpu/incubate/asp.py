"""Automatic SParsity — 2:4 semi-structured pruning (reference:
python/paddle/incubate/asp/asp.py).

The reference maintains per-parameter masks and re-applies them inside a
decorated optimizer so pruned weights stay zero through training (Ampere
sparse-tensor-core format). The same n:m scheme is useful on TPU as a
model-compression path (XLA has no sparse MXU mode, so the win is
memory/regularization, not FLOPs — documented honestly here).

API parity: set_excluded_layers / reset_excluded_layers / decorate /
prune_model / calculate_density.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "check_sparsity_2_4", "create_mask_2_4"]

_excluded = set()


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x):
    v = np.asarray(unwrap(x))
    return float((v != 0).sum() / max(v.size, 1))


def create_mask_2_4(w):
    """Best 2-of-4 mask along the last axis: keep the two largest |w| in
    every group of four (the reference's MaskAlgo.MASK_2D_BEST per row)."""
    v = np.asarray(unwrap(w))
    flat = v.reshape(-1)
    pad = (-flat.size) % 4
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, 4))
    order = np.argsort(groups, axis=1)
    mask = np.ones_like(groups, bool)
    np.put_along_axis(mask, order[:, :2], False, axis=1)  # drop 2 smallest
    mask = mask.reshape(-1)[:v.size].reshape(v.shape)
    return mask


def check_sparsity_2_4(w):
    v = np.asarray(unwrap(w)).reshape(-1)
    pad = (-v.size) % 4
    if pad:
        v = np.concatenate([v, np.zeros(pad, v.dtype)])
    return bool(((v.reshape(-1, 4) != 0).sum(1) <= 2).all())


def _prunable(model):
    from ..nn.layer.common import Linear
    for name, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, Linear) and layer.weight is not None:
            pname = f"{name}.weight" if name else "weight"
            if pname not in _excluded and layer.weight.shape[-1] % 4 == 0:
                yield pname, layer


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every prunable Linear weight; masks are stored
    on the layer for the decorated optimizer to re-apply."""
    if (n, m) != (2, 4):
        raise NotImplementedError("only 2:4 sparsity is supported")
    masks = {}
    for pname, layer in _prunable(model):
        mask = create_mask_2_4(layer.weight)
        layer._asp_mask = jnp.asarray(mask)
        layer.weight.set_value(Tensor(unwrap(layer.weight) * layer._asp_mask))
        masks[pname] = mask
    return masks


class ASPOptimizerWrapper:
    """reference OptimizerWithSparsityGuarantee: after every step, zero
    the pruned coordinates again so training cannot resurrect them."""

    def __init__(self, optimizer, model=None):
        self._opt = optimizer
        self._model = model

    def __getattr__(self, k):
        return getattr(self._opt, k)

    def _reapply(self):
        if self._model is None:
            return
        for _, layer in self._model.named_sublayers(include_self=True):
            mask = getattr(layer, "_asp_mask", None)
            if mask is not None:
                layer.weight.set_value(
                    Tensor(unwrap(layer.weight) * mask))

    def step(self):
        out = self._opt.step()
        self._reapply()
        return out

    def clear_grad(self, *a, **k):
        return self._opt.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        out = self._opt.minimize(loss, *a, **k)
        self._reapply()
        return out


def decorate(optimizer, model=None):
    return ASPOptimizerWrapper(optimizer, model)
