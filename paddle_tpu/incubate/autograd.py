"""Functional differentiation API (reference:
python/paddle/incubate/autograd/functional.py — vjp/jvp/Jacobian/Hessian).

On JAX these are native program transforms; the paddle surface maps
directly onto jax.vjp / jax.jvp / jax.jacobian / jax.hessian — including
forward-mode, which the reference implements with its own primitive
rules (incubate/autograd/primx.py) and we get from the tracer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _uw_tree(x):
    return jax.tree_util.tree_map(
        lambda t: unwrap(t) if isinstance(t, Tensor) else jnp.asarray(t), x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap_tree(x):
    return jax.tree_util.tree_map(Tensor, x)


def _pure(func):
    def f(*raws):
        out = func(*[Tensor(r) for r in raws])
        return jax.tree_util.tree_map(
            lambda t: unwrap(t) if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
    return f


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def vjp(func, xs, v=None):
    """→ (func(xs), vector-Jacobian product). v defaults to ones like the
    output (reference functional.py:50)."""
    raws = [_uw_tree(x) for x in _as_list(xs)]
    out, vjp_fn = jax.vjp(_pure(func), *raws)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = _uw_tree(v if not isinstance(v, (list, tuple)) or
                       isinstance(out, (list, tuple)) else v)
        if isinstance(v, (list, tuple)) and not isinstance(out, (list, tuple)):
            cot = _uw_tree(v[0])
    grads = vjp_fn(cot)
    grads = list(grads) if isinstance(xs, (list, tuple)) else grads[0]
    return _wrap_tree(out), _wrap_tree(grads)


def jvp(func, xs, v=None):
    """→ (func(xs), Jacobian-vector product) via true forward mode."""
    raws = [_uw_tree(x) for x in _as_list(xs)]
    if v is None:
        tans = [jnp.ones_like(r) for r in raws]
    else:
        tans = [_uw_tree(t) for t in _as_list(v)]
    out, tangent = jax.jvp(_pure(func), tuple(raws), tuple(tans))
    return _wrap_tree(out), _wrap_tree(tangent)


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]


def grad(func, xs, v=None):
    return vjp(func, xs, v)[1]


class Jacobian:
    """Lazy full Jacobian (reference functional.py Jacobian): J[:] gives
    the (out_size, in_size)-flattened matrix; rows/cols index into it."""

    def __init__(self, func, xs, is_batched=False):
        self._xs = _uw_tree(xs if not isinstance(xs, (list, tuple)) else
                            xs[0])
        self._func = func
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is None:
            jac = jax.jacobian(_pure(self._func))(self._xs)
            if self._is_batched:
                # (B, out..., B, in...) diag over batch → (B, out, in)
                b = self._xs.shape[0]
                out_sz = int(jnp.size(jac)) // (b * b * int(
                    jnp.prod(jnp.asarray(self._xs.shape[1:]))))
                j = jac.reshape(b, out_sz, b, -1)
                self._mat = jnp.stack([j[i, :, i] for i in range(b)])
            else:
                out_shape = jax.eval_shape(_pure(self._func), self._xs).shape
                self._mat = jac.reshape(int(jnp.prod(jnp.asarray(
                    out_shape, jnp.int64))) if out_shape else 1, -1)
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._compute()[idx])

    @property
    def shape(self):
        return list(self._compute().shape)


class Hessian:
    """Lazy Hessian of a scalar-output function (reference Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        self._xs = _uw_tree(xs if not isinstance(xs, (list, tuple)) else
                            xs[0])
        self._func = func
        self._mat = None

    def _compute(self):
        if self._mat is None:
            h = jax.hessian(lambda x: jnp.squeeze(_pure(self._func)(x)))(
                self._xs)
            n = int(jnp.size(self._xs))
            self._mat = h.reshape(n, n)
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._compute()[idx])

    @property
    def shape(self):
        return list(self._compute().shape)
