"""paddle.incubate.autotune.set_config parity (reference:
python/paddle/incubate/autotune.py — toggles kernel/layout/dataloader
auto-tuning in the fluid runtime).

On TPU the equivalents are either always-on or owned elsewhere, so this
records and validates the config and routes the one knob that has a
live counterpart:

  * kernel:    XLA:TPU autotunes tilings/fusion during compilation —
               always on, nothing to enable.
  * layout:    XLA picks layouts; NHWC-native convs are the default in
               paddle_tpu.nn already.
  * dataloader: tune_num_workers maps to the DataLoader's worker pool —
               recorded here and read by paddle_tpu.io as a default.

Offline search over the knobs XLA does NOT own (batch/remat/flash
blocks/grad-accum) lives in tools/autotune.py.
"""
from __future__ import annotations

import json

__all__ = ["set_config"]

_config = {"kernel": {"enable": True},
           "layout": {"enable": True},
           "dataloader": {"enable": False}}


def set_config(config=None):
    """reference autotune.py:47. Accepts a dict (or a path to a JSON
    file) with any of the keys kernel / layout / dataloader; unknown
    keys raise, matching the reference's warning-and-ignore but loudly
    (a typo here silently disabling tuning is the failure mode)."""
    global _config
    if config is None:
        for v in _config.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"set_config expects dict, json path or None, "
                        f"got {type(config)}")
    unknown = set(config) - set(_config)
    if unknown:
        raise ValueError(f"unknown autotune sections {sorted(unknown)}; "
                         f"valid: {sorted(_config)}")
    for k, v in config.items():
        if not isinstance(v, dict):
            raise TypeError(f"section {k!r} must be a dict, got {type(v)}")
        _config[k] = {**_config[k], **v}


def get_config():
    """Current autotune config (introspection helper; the reference
    keeps this state internal to fluid)."""
    return {k: dict(v) for k, v in _config.items()}
