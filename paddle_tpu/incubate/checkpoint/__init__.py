"""paddle.incubate.checkpoint (reference: python/paddle/incubate/
checkpoint/__init__.py re-exporting base auto_checkpoint)."""
from . import auto_checkpoint  # noqa: F401

__all__ = []
