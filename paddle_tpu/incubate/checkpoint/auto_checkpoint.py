"""auto_checkpoint parity (reference: python/paddle/base/incubate/
checkpoint/auto_checkpoint.py — PaddleCloud's env-driven epoch-resume
loop: `for epoch in acp.train_epoch_range(N): ...` transparently skips
epochs a previous incarnation of the job completed).

TPU-native shape: the heavy state (params/opt/rng) already has an
atomic resume story in paddle_tpu.utils.checkpoint; what this module
adds is the reference's EPOCH-RANGE bookkeeping — a tiny status file,
written atomically after each completed epoch, consulted at start.
Enabled by env like the reference (theirs: PADDLE_RUNNING_ENV=
PaddleCloud + job env; ours: PT_AUTO_CKPT_DIR pointing at the job's
checkpoint directory). Without the env the range degrades to plain
`range(max_epoch_num)`, exactly like the reference off PaddleCloud.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["AutoCheckpointChecker", "train_epoch_range"]


class AutoCheckpointChecker:
    """reference auto_checkpoint.py:70 — decides whether auto
    checkpointing is active and where state lives."""

    def __init__(self):
        self._dir = os.environ.get("PT_AUTO_CKPT_DIR", "")
        self.job_id = os.environ.get("PT_JOB_ID",
                                     os.environ.get("PADDLE_JOB_ID",
                                                    "default"))
        try:
            self.save_checkpoint_inter = int(os.environ.get(
                "PT_CKPT_SAVE_INTER", "900"))
        except ValueError:
            self.save_checkpoint_inter = 900

    def valid(self):
        return bool(self._dir)

    def get_job_path(self):
        return os.path.join(self._dir, self.job_id)

    def get_range_checkpoint_path(self, name):
        return os.path.join(self.get_job_path(), f"range_{name}.json")


def _get_checker():
    return AutoCheckpointChecker()


def _load_status(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"epoch_no": -1}


def _save_status(path, status):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(status, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      name="0"):
    """reference auto_checkpoint.py:615. Yields epoch indices,
    SKIPPING epochs recorded complete by a previous run of the same
    job; records completion after each yielded epoch's body finishes
    (i.e. when the generator is resumed). Writes are throttled by
    save_checkpoint_inter seconds (plus one final write on
    exhaustion), so a kill re-runs the interrupted epoch AND any
    epochs completed since the last banked write — set
    save_checkpoint_inter=0 to bank every epoch and re-run only the
    interrupted one."""
    checker = _get_checker()
    if not checker.valid():
        # off-cloud: plain range, like the reference off PaddleCloud
        yield from range(max_epoch_num)
        return
    inter = (checker.save_checkpoint_inter
             if save_checkpoint_inter is None else save_checkpoint_inter)
    path = checker.get_range_checkpoint_path(name)
    status = _load_status(path)
    start = int(status.get("epoch_no", -1)) + 1
    last_write = time.monotonic()
    dirty = False
    for epoch in range(start, max_epoch_num):
        yield epoch
        # body completed — bank it (throttled)
        status["epoch_no"] = epoch
        dirty = True
        now = time.monotonic()
        if now - last_write >= inter:
            _save_status(path, status)
            last_write = now
            dirty = False
    if dirty:
        _save_status(path, status)
