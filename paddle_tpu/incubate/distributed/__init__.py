"""paddle.incubate.distributed (reference: python/paddle/incubate/
distributed/) — the models.moe surface; fleet re-exports live in
paddle_tpu.distributed.fleet."""
from . import models  # noqa: F401
