"""paddle.incubate.distributed.models.moe (reference layout)."""
from . import gate  # noqa: F401
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
