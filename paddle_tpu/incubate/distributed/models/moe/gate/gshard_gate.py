"""reference: gate/gshard_gate.py — top-2 router with the GShard
load-balance loss (E^2 * mean(c_e * m_e)), capacity limiting and
random second-expert routing. Capacity limiting is a cumsum rank test
(jit-friendly) instead of the reference's host-side limit_by_capacity
kernel: slots past the per-expert capacity are marked -1, matching the
reference's contract."""
import math

import jax
import jax.numpy as jnp

from ......_core.tensor import Tensor, apply, unwrap
from ......_core.state import prng
from .naive_gate import NaiveGate


def _limit_by_capacity(topk_idx, tot_expert, capacity):
    """(T, k) expert ids -> same with over-capacity entries set to -1.
    Rank = arrival order, slot-major (slot 0 of every token first),
    via the shared expert_slot_positions helper."""
    from ......parallel.moe import expert_slot_positions
    pos = expert_slot_positions(topk_idx, tot_expert)
    return jnp.where(pos < capacity, topk_idx, -1)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        assert topk == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, x):
        topk_val, topk_idx, gate_score = super().forward(
            x, return_all_scores=True)
        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * x.shape[0])
        tot = self.tot_expert

        def aux(score, idx):
            s = score.shape[0]
            c_e = jnp.sum(jax.nn.one_hot(idx.reshape(-1), tot,
                                         dtype=jnp.float32), axis=0) / s
            m_e = jnp.mean(jax.nn.softmax(score, axis=1), axis=0)
            return jnp.mean(c_e * m_e) * (self.num_expert ** 2)

        self.set_loss(apply(aux, gate_score, topk_idx, name="gshard_aux"))

        idx = _limit_by_capacity(unwrap(topk_idx), tot, capacity)
        if self.random_routing and self.training:
            # reference: the 2nd expert is kept only with probability
            # proportional to its gate value (2*val > U[0,1])
            u = jax.random.uniform(prng.next_key(),
                                   (idx.shape[0],), jnp.float32)
            keep2 = (2.0 * unwrap(topk_val)[:, 1] > u)
            idx = idx.at[:, 1].set(jnp.where(keep2, idx[:, 1], -1))
        return topk_val, Tensor(idx)
