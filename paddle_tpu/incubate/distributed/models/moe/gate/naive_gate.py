"""reference: gate/naive_gate.py — plain linear router, top-k scores."""
from ...... import nn
from .base_gate import BaseGate


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        import paddle_tpu as pt
        gate = self.gate(inp)
        val, idx = pt.topk(gate, k=self.top_k, axis=-1)
        if return_all_scores:
            return val, idx, gate
        return val, idx
