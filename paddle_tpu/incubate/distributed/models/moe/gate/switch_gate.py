"""reference: gate/switch_gate.py — Switch Transformer top-1 router:
multiplicative uniform noise while training, softmax score, capacity
limit, and the Switch aux loss E * sum(fraction_e * prob_e)."""
import math

import jax
import jax.numpy as jnp

from ......_core.tensor import Tensor, apply, unwrap
from ......_core.state import prng
from .gshard_gate import _limit_by_capacity
from .naive_gate import NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        assert topk == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity
        self.group = group

    def forward(self, inp):
        score = self.gate(inp)
        if self.training:
            def noisy(s):
                noise = jax.random.uniform(prng.next_key(), s.shape,
                                           jnp.float32)
                return s + (noise * 2 * self.switch_eps
                            + 1.0 - self.switch_eps)
            score = apply(noisy, score, name="switch_noise")
        import paddle_tpu as pt
        score = pt.nn.functional.softmax(score, axis=-1)
        top1_val, top1_idx = pt.topk(score, k=1, axis=-1)

        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * inp.shape[0])
        idx = _limit_by_capacity(unwrap(top1_idx), self.tot_expert,
                                 capacity)
        tot = self.tot_expert

        def aux(sc, kept):
            valid = jax.nn.one_hot(jnp.where(kept < 0, 0, kept)[:, 0],
                                   tot, dtype=jnp.float32)
            valid = valid * (kept[:, :1] >= 0)
            # reference normalizes BOTH factors by the capacity-kept
            # assignment count (valid_idx.numel()), not by T — the
            # scales only coincide while the cap never binds
            kept_n = jnp.maximum(jnp.sum(valid), 1.0)
            fraction = jnp.sum(valid, axis=0) / kept_n
            prob = jnp.sum(sc, axis=0) / kept_n
            return jnp.sum(fraction * prob) * tot

        self.set_loss(apply(aux, score, Tensor(idx), name="switch_aux"))
        return top1_val, Tensor(idx)
