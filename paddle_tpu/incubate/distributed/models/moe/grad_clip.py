"""ClipGradForMOEByGlobalNorm (reference: python/paddle/incubate/
distributed/models/moe/grad_clip.py).

Expert grads live only on their EP shard, so a plain global norm would
double-count replicated params or miss remote expert norms. The
reference splits params into normal/expert groups, all_reduces the
expert-group squared norm over moe_group, and clips everything by the
combined norm. Here the same split applies; the expert-group reduction
uses our collective all_reduce when a group is given (on the SPMD path
GSPMD already derives this — this class serves the eager tier)."""
from __future__ import annotations

import jax.numpy as jnp

from ....._core.tensor import Tensor
from .....nn.clip import ClipGradBase

__all__ = ["ClipGradForMOEByGlobalNorm"]


def _sq_norm(params_grads):
    sq = [jnp.sum(jnp.square(g._value.astype(jnp.float32)))
          for p, g in params_grads
          if g is not None and getattr(p, "need_clip", True)]
    if not sq:
        return None
    return sum(sq[1:], sq[0])


class ClipGradForMOEByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__()
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.moe_group = moe_group
        if moe_group is not None and getattr(moe_group, "nranks", 1) > 1:
            assert is_expert_param_func is not None, (
                "When moe group size > 1, a function for selecting "
                "expert params must be specified.")
        self.is_expert_param_func = is_expert_param_func

    def __str__(self):
        return f"Gradient Clip By GlobalNorm, global_norm={self.clip_norm:f}"

    def _dygraph_clip(self, params_grads):
        normal, moe = [], []
        if self.is_expert_param_func is not None:
            for p, g in params_grads:
                (moe if self.is_expert_param_func(p)
                 else normal).append((p, g))
        else:
            normal = list(params_grads)

        gn = _sq_norm(normal)
        gm = _sq_norm(moe)
        if gm is not None and self.moe_group is not None and \
                getattr(self.moe_group, "nranks", 1) > 1:
            from .....distributed import all_reduce
            t = Tensor(gm)
            all_reduce(t, group=self.moe_group)
            gm = t._value
        if gn is None and gm is None:
            return params_grads
        total = (gn if gm is None else
                 gm if gn is None else gn + gm)
        gnorm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value * scale).astype(g.dtype))))
        return out
