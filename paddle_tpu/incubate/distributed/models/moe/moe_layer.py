"""MoELayer over ARBITRARY expert Layers (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py — the
FastMoE-style scatter/gather over NCCL alltoall).

TPU-native dispatch: the gate's (topk_val, topk_idx) feed a
capacity-bounded dispatch/combine pair (same construction as
parallel/moe.top_k_gating); each expert then runs on its gathered
(capacity, d_model) slab — a static Python loop over experts (they are
separate Layers with separate weights, so there is nothing to stack),
each slab computed with two einsums that GSPMD turns into all_to_all
when the token axis is sharded. By default the layer never drops a
token the gate admitted (capacity covers the worst case, like the
reference layer — dropping is the GATE's job via -1 ids, which
contribute zero); setting capacity_factor opts into a tighter
dispatch tensor with layer-level drops."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .....parallel.moe import expert_slot_positions
from ....._core.tensor import apply
from .....nn.layer.layers import Layer
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


def _dispatch_combine(topk_idx, topk_val, tot_expert, capacity):
    """(T,k) ids (−1 = dropped) + (T,k) raw scores →
    dispatch (T,E,C) 0/1 and combine (T,E,C) float32 tensors."""
    valid = topk_idx >= 0
    safe_idx = jnp.where(valid, topk_idx, 0)
    # the gate's values are used AS-IS (reference moe_layer.py:494
    # bmm(value, x) — normalization is the gate's business; dropped
    # slots contribute zero)
    vals = jnp.where(valid, topk_val.astype(jnp.float32), 0.0)
    pos = expert_slot_positions(topk_idx, tot_expert)      # (T, k)
    keep = valid & (pos < capacity)

    disp = (jax.nn.one_hot(safe_idx, tot_expert, dtype=jnp.float32)[..., None]
            * jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                             dtype=jnp.float32)[..., None, :])
    disp = disp * keep[..., None, None]
    dispatch = disp.sum(1)                                  # (T, E, C)
    combine = (disp * vals[..., None, None]).sum(1)         # (T, E, C)
    return dispatch, combine


class MoELayer(Layer):
    """reference moe_layer.py:261. gate: dict config ({"type": "gshard"|
    "switch"|"naive"|None, "top_k": int}) or a BaseGate instance.
    moe_group/mp_group are accepted for signature parity — expert
    placement on TPU is declared by sharding the token axis over the
    mesh, not by process groups."""

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, recompute_ctx=None):
        super().__init__()
        self.d_model = d_model
        self.experts = experts if isinstance(experts, Layer) else \
            self._wrap_experts(experts)
        self.num_expert = len(experts)
        self.world_size = 1
        self.recompute_interval = recompute_interval
        if gate is None:
            gate = {}
        if isinstance(gate, dict):
            top_k = gate.get("top_k", 2)
            kind = gate.get("type", "gshard")
            if kind in ("naive", None):
                # reference moe_layer.py:370: type None routes to
                # NaiveGate with the requested top_k, same as "naive"
                gate = NaiveGate(d_model, self.num_expert,
                                 self.world_size, topk=top_k)
            elif kind == "gshard":
                gate = GShardGate(d_model, self.num_expert,
                                  self.world_size, topk=2)
                top_k = 2
            elif kind == "switch":
                gate = SwitchGate(d_model, self.num_expert,
                                  self.world_size, topk=1)
                top_k = 1
            else:
                raise AssertionError(
                    f"We only support naive/gshard/switch gate, "
                    f"but got {kind!r}")
            self.top_k = top_k
        elif isinstance(gate, BaseGate):
            self.top_k = getattr(gate, "top_k", 2)
        else:
            raise AssertionError(f"gate config error: {gate!r}")
        self.gate = gate
        # None = dispatch every token the gate admitted (the reference
        # layer never drops — dropping is the GATE's job via -1 ids);
        # a float opts into a tighter capacity-bounded dispatch tensor
        # (memory: T x E x C)
        self.capacity_factor = None

    def _wrap_experts(self, experts):
        from .....nn import LayerList
        return LayerList(list(experts))

    def forward(self, inp):
        shape = inp.shape
        d = shape[-1]
        tokens = inp.reshape([-1, d])
        T = tokens.shape[0]
        topk_val, topk_idx = self.gate(tokens)
        if self.capacity_factor is None:
            # every admitted token gets a slot (worst case: all k*T
            # assignments land on one expert) — layer-level drops are
            # impossible, matching the reference
            capacity = self.top_k * T
        else:
            capacity = max(1, math.ceil(
                self.capacity_factor * self.top_k * T / self.num_expert))

        def build(idx, val):
            return _dispatch_combine(idx, val, self.num_expert, capacity)

        dispatch, combine = apply(build, topk_idx, topk_val,
                                  name="moe_dispatch", multi=True)

        out = None
        for e in range(self.num_expert):
            # gather expert e's slab: (C, d) = dispatch[:, e, :].T @ x
            def gather(dsp, x, _e=e):
                return jnp.einsum("tc,td->cd", dsp[:, _e, :], x)

            slab = apply(gather, dispatch, tokens, name="moe_gather")
            y = self.experts[e](slab)

            def scatter(cmb, ye, _e=e):
                return jnp.einsum("tc,cd->td", cmb[:, _e, :],
                                  ye.astype(jnp.float32))

            contrib = apply(scatter, combine, y, name="moe_scatter")
            out = contrib if out is None else out + contrib

        def finish(o, x):
            return o.astype(x.dtype)

        out = apply(finish, out, tokens, name="moe_out")
        return out.reshape(shape)
