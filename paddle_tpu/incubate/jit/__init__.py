"""paddle.incubate.jit (reference: python/paddle/incubate/jit/
{__init__,inference_decorator}.py)."""
from .inference_decorator import inference  # noqa: F401

__all__ = []
