"""paddle.incubate.jit.inference parity (reference:
python/paddle/incubate/jit/inference_decorator.py — wraps a function
or method so its first call converts it to a static inference model
under the Predictor and later calls run the compiled engine; the
saved model caches across processes).

TPU-native: trace-once jit IS the inference engine, so the decorator
is a shape-keyed `jax.jit` over the unwrapped function with an
optional PERSISTENT cache — with cache_static_model=True the traced
program is serialized via jax.export to save_model_dir (default
~/.cache/paddle_tpu/inference_models/<fn>) and a later process
deserializes instead of retracing, the cross-process compile cache
the reference gets from its saved inference model. TRT/CINN/IR knobs
are accepted and ignored (XLA owns those jobs here); precision_mode
'float16'/'bfloat16' casts floating inputs at the boundary.
"""
from __future__ import annotations

import functools
import inspect
import os
from pathlib import Path

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, unwrap

__all__ = ["inference"]


class InferenceEngine:
    def __init__(self, func, used_as_at_decorator, cache_static_model=False,
                 save_model_dir=None, precision_mode=None, **knobs):
        self.func = func
        self.used_as_at_decorator = used_as_at_decorator
        self.sig = inspect.signature(func)
        if any(p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
               for p in self.sig.parameters.values()):
            raise ValueError(
                f"your function named {func.__name__} definition has * or "
                "** args, please modify your function definition")
        self.arg_names = list(self.sig.parameters)
        if used_as_at_decorator:
            assert self.arg_names and self.arg_names[0] == "self"
        self.cache_static_model = bool(cache_static_model)
        if self.cache_static_model and used_as_at_decorator:
            # a method's compiled program bakes in ONE instance's
            # weights; a disk cache shared across instances/processes
            # would silently serve the wrong model's outputs
            raise NotImplementedError(
                "cache_static_model=True on a METHOD is unsupported: the "
                "exported program captures one instance's weights. Use "
                "paddle_tpu.jit.save + inference.Predictor for "
                "cross-process model caching.")
        if save_model_dir is None:
            save_model_dir = os.path.join(
                Path.home(), ".cache", "paddle_tpu", "inference_models")
        # identity goes beyond __name__: two same-named functions with
        # identical shapes must not load each other's exports
        import hashlib
        ident = hashlib.sha1(
            f"{func.__module__}.{getattr(func, '__qualname__', func.__name__)}"
            .encode()).hexdigest()[:8]
        self.save_model_dir = os.path.join(
            save_model_dir, f"{func.__name__}_{ident}")
        self.precision_mode = precision_mode
        self._compiled = {}     # function-form: key -> callable(*raws)
        # method-form: per-INSTANCE caches that die with the instance
        # (compiled closures bake the instance's weights; a map keyed
        # by id would pin every instance alive forever)
        import weakref
        self._per_instance = weakref.WeakKeyDictionary()

    # -- helpers -------------------------------------------------------
    def _cast(self, raw):
        if self.precision_mode in ("float16", "bfloat16") and \
                jnp.issubdtype(raw.dtype, jnp.floating):
            return raw.astype(self.precision_mode)
        return raw

    def _key(self, tensor_args, static_args):
        # repr() the static values: config args are often lists/dicts,
        # which would make the key unhashable
        return (tuple((tuple(a.shape), str(a.dtype)) for a in tensor_args),
                tuple(sorted((k, repr(v))
                             for k, v in static_args.items())))

    def _export_path(self, key):
        import hashlib
        h = hashlib.sha1(repr(key).encode()).hexdigest()[:16]
        return os.path.join(self.save_model_dir, f"infer_{h}.pdexport")

    def _build(self, key, tensor_args, static_args, self_obj):
        """Compile (or load) the program for this shape signature."""
        path = self._export_path(key)
        if self.cache_static_model and os.path.exists(path):
            from jax import export as jexport
            with open(path, "rb") as f:
                exported = jexport.deserialize(f.read())
            return lambda *raws: exported.call(*raws)

        # hold the instance WEAKLY: the cache value must not keep its
        # own WeakKeyDictionary key alive. The jitted executable bakes
        # the weights as trace-time constants; only a RE-trace (rare:
        # jax weak-type promotion) needs the instance again.
        import weakref
        self_ref = weakref.ref(self_obj) if self_obj is not None else None

        def pure(*raws):
            args = [Tensor(r) for r in raws]
            it = iter(args)
            pos, kw = [], {}
            for name, param in self.sig.parameters.items():
                if name == "self":
                    continue
                v = static_args[name] if name in static_args else next(it)
                if param.kind == param.KEYWORD_ONLY:
                    kw[name] = v    # a bare '*' makes these kw-only
                else:
                    pos.append(v)
            if self_ref is not None:
                obj = self_ref()
                if obj is None:
                    raise RuntimeError(
                        "inference: the decorated method's instance was "
                        "garbage-collected before a retrace")
                out = self.func(obj, *pos, **kw)
            else:
                out = self.func(*pos, **kw)
            return jax.tree_util.tree_map(
                lambda t: unwrap(t) if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        jitted = jax.jit(pure)
        if self.cache_static_model:
            from jax import export as jexport
            structs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                       for a in tensor_args]
            exported = jexport.export(jitted)(*structs)
            os.makedirs(self.save_model_dir, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(exported.serialize())
            os.replace(tmp, path)
        return jitted

    # -- call ----------------------------------------------------------
    def run(self, self_obj, *args, **kwargs):
        # real signature binding: defaults apply, typo'd/unknown kwargs
        # raise TypeError exactly like the undecorated function
        if self.used_as_at_decorator:
            ba = self.sig.bind(self_obj, *args, **kwargs)
        else:
            ba = self.sig.bind(*args, **kwargs)
        ba.apply_defaults()
        tensor_args, static_args = [], {}
        for name in self.arg_names:
            if name == "self":
                continue
            v = ba.arguments[name]
            if isinstance(v, Tensor):
                tensor_args.append(self._cast(unwrap(v)))
            elif hasattr(v, "shape") and hasattr(v, "dtype"):
                tensor_args.append(self._cast(jnp.asarray(v)))
            else:
                static_args[name] = v
        key = self._key(tensor_args, static_args)
        # per-instance cache for methods (the traced closure bakes THIS
        # instance's weights; entries die with the instance). The key
        # itself is instance-free so the persistent export path stays
        # stable across processes.
        cache = (self._compiled if self_obj is None
                 else self._per_instance.setdefault(self_obj, {}))
        fn = cache.get(key)
        if fn is None:
            fn = self._build(key, tensor_args, static_args, self_obj)
            cache[key] = fn
        import time as _time
        t0 = _time.perf_counter()
        out = fn(*tensor_args)
        # compile telemetry: the shape key IS the cache key, so a new
        # key is a (re)trace — counted + timed in the global registry;
        # a compile also captures the executable's XLA cost/memory
        # analysis and every call feeds the device-telemetry MFU window
        from ...observability import device_telemetry as _dt
        from ...observability.compile_telemetry import REGISTRY
        label = f"incubate.inference:{self.func.__qualname__}"
        compiled = REGISTRY.note_call(label, key,
                                      _time.perf_counter() - t0)
        if compiled:
            _dt.COSTS.capture(label, key, fn, tuple(tensor_args))
        _dt.COSTS.note_executed(label, key)
        return jax.tree_util.tree_map(Tensor, out)


def inference(function=None, cache_static_model=False, **kwargs):
    """reference inference_decorator.py. Use bare (`@inference`) or
    configured (`@inference(cache_static_model=True)`), on functions or
    methods. Shape changes retrace (and re-cache) automatically."""
    def decorate(func):
        used_as_at = "self" in inspect.signature(func).parameters
        engine = InferenceEngine(func, used_as_at,
                                 cache_static_model=cache_static_model,
                                 **kwargs)

        if used_as_at:
            @functools.wraps(func)
            def method(self, *args, **kw):
                return engine.run(self, *args, **kw)
            method._inference_engine = engine
            return method

        @functools.wraps(func)
        def wrapper(*args, **kw):
            return engine.run(None, *args, **kw)
        wrapper._inference_engine = engine
        return wrapper

    if function is not None:
        return decorate(function)
    return decorate
