"""paddle.incubate.layers (reference: python/paddle/incubate/layers/)."""
from . import nn  # noqa: F401
from .nn import (  # noqa: F401
    partial_concat, partial_sum, pow2_decay_with_linear_warmup,
    shuffle_batch,
)

__all__ = []
