"""Selected incubate.layers ops (reference: python/paddle/incubate/
layers/nn.py — fluid contrib layers). The general-purpose ones are
implemented TPU-native; the static-graph rec-sys specials that create
global program state through LayerHelper (pyramid hash, tdm samplers,
rank_attention, batch_fc, fused_bn_add_act, seqpool_cvm) raise with
guidance — their jobs are covered by the PS tier + standard layers."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap
from ..._core.state import prng
from ...optimizer.lr import LRScheduler

__all__ = [
    "shuffle_batch",
    "partial_concat",
    "partial_sum",
    "pow2_decay_with_linear_warmup",
]


def shuffle_batch(x, seed=None):
    """reference nn.py:274: randomly permute the leading dims' rows
    (last dim rides along). seed=None draws from the framework PRNG
    stream; an int seed is deterministic."""
    # draw the key OUTSIDE fn: the tape's backward re-executes fn for
    # its vjp, and a fresh next_key() there would backprop through a
    # DIFFERENT permutation than the forward ran
    if seed is None:
        key = prng.next_key()
    else:
        key = jax.random.PRNGKey(int(unwrap(seed))
                                 if isinstance(seed, Tensor)
                                 else int(seed))

    def fn(a):
        lead = a.shape[:-1]
        flat = a.reshape(-1, a.shape[-1])
        perm = jax.random.permutation(key, flat.shape[0])
        return flat[perm].reshape(*lead, a.shape[-1])
    return apply(fn, x, name="shuffle_batch")


def _col_slice(ts, start_index, length):
    widths = {t.shape[1] for t in ts}
    if len(widths) != 1:
        # numpy slicing would silently CLAMP a narrower tensor's slice,
        # concatenating/summing the wrong shape with no error
        raise ValueError(
            f"partial op: all inputs must share the column count, got "
            f"{sorted(widths)}")
    ncol = ts[0].shape[1]
    start = start_index if start_index >= 0 else start_index + ncol
    stop = ncol if length < 0 else start + length
    if not (0 <= start <= ncol and start <= stop <= ncol):
        raise ValueError(
            f"partial op: slice [{start}:{stop}) out of bounds for "
            f"{ncol} columns")
    return start, stop


def partial_concat(input, start_index=0, length=-1):
    """reference nn.py:346: per-tensor column slice, concatenated along
    dim 1. 2-D inputs only (the reference's documented contract)."""
    ts = input if isinstance(input, (list, tuple)) else [input]
    for t in ts:
        if len(t.shape) != 2:
            raise ValueError("partial_concat only supports 2-D tensors")
    start, stop = _col_slice(ts, start_index, length)

    def fn(*raws):
        return jnp.concatenate([r[:, start:stop] for r in raws], axis=1)
    return apply(fn, *ts, name="partial_concat")


def partial_sum(input, start_index=0, length=-1):
    """reference nn.py:426: per-tensor column slice, summed elementwise."""
    ts = input if isinstance(input, (list, tuple)) else [input]
    for t in ts:
        if len(t.shape) != 2:
            raise ValueError("partial_sum only supports 2-D tensors")
    start, stop = _col_slice(ts, start_index, length)

    def fn(*raws):
        acc = raws[0][:, start:stop]
        for r in raws[1:]:
            acc = acc + r[:, start:stop]
        return acc
    return apply(fn, *ts, name="partial_sum")


class Pow2DecayWithLinearWarmup(LRScheduler):
    """The schedule behind reference nn.py:1297 (a static-graph op
    updating an lr variable in place): linear warmup 0 → base_lr over
    warmup_steps, then a squared decay down to end_lr at total_steps."""

    def __init__(self, warmup_steps, total_steps, base_lr, end_lr,
                 last_epoch=-1, verbose=False):
        assert warmup_steps <= total_steps, \
            "warmup_steps cannot be larger than total_steps"
        self.warmup_steps = float(warmup_steps)
        self.total_steps = float(total_steps)
        self.end_lr = float(end_lr)
        super().__init__(base_lr, last_epoch, verbose)

    def get_lr(self):
        step = max(0, self.last_epoch)
        if step < self.warmup_steps:
            return self.base_lr * (step + 1) / self.warmup_steps
        frac = min(1.0, (step - self.warmup_steps)
                   / max(1.0, self.total_steps - self.warmup_steps))
        factor = (1.0 - frac) ** 2
        return (self.base_lr - self.end_lr) * factor + self.end_lr


def pow2_decay_with_linear_warmup(warmup_steps, total_steps, base_lr,
                                  end_lr, dtype="float32", name=None):
    """reference nn.py:1297. The reference raises in dygraph and only
    works as a static op; here the schedule is a first-class
    LRScheduler usable anywhere an optimizer takes one."""
    return Pow2DecayWithLinearWarmup(warmup_steps, total_steps,
                                     base_lr, end_lr)


def __getattr__(name):
    _STATIC_ONLY = {"fused_seqpool_cvm", "search_pyramid_hash",
                    "tdm_child", "tdm_sampler", "rank_attention",
                    "batch_fc", "fused_bn_add_act", "correlation",
                    "fused_embedding_seq_pool", "multiclass_nms2"}
    if name in _STATIC_ONLY:
        raise NotImplementedError(
            f"incubate.layers.{name} is a fluid static-graph contrib op "
            "that creates program-global state; on paddle_tpu use the "
            "equivalent standard surface (PS tier for sparse rec-sys "
            "tables, nn layers + XLA fusion for fused blocks)")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
