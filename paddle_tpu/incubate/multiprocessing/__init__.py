"""paddle.incubate.multiprocessing parity (reference:
python/paddle/incubate/multiprocessing/{__init__,reductions}.py —
shared-memory tensor passing between processes via ForkingPickler
reductions over LoDTensor file descriptors).

TPU-native shape: device arrays are owned by the XLA runtime and are
not shareable across OS processes, so sharing happens at host level —
a Tensor crossing a process boundary travels as a POSIX shared-memory
block (multiprocessing.shared_memory): one copy into shm at send, one
copy out at receive (the rebuilt tensor owns its memory so the sender
can unlink; a device_put would copy regardless). The payload itself
stays a few bytes — name/shape/dtype — instead of the tensor bytes.
Gradients/tape state do not cross (same as the reference, which ships
values only).

Usage matches the reference: `import paddle_tpu.incubate.
multiprocessing as mp` then use mp.Process/Queue/... — the module
re-exports the stdlib multiprocessing namespace with the reductions
installed.
"""
from .reductions import init_reductions

__all__ = []

from multiprocessing import *  # noqa: F401,F403

init_reductions()
