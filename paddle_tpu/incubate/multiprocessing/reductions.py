"""ForkingPickler reductions for paddle_tpu.Tensor (reference:
python/paddle/incubate/multiprocessing/reductions.py).

Send side: the host view of the array is copied once into a POSIX
shared-memory block; the pickle payload is (shm name, shape, dtype).
Receive side: the child maps the block and materializes the tensor.
Blocks are held by the sender until process exit (atexit sweep) —
the reference's file_system strategy lifetime — because a payload can
sit in a Queue long after the source tensor is gone; POSIX refcounting
keeps receiver mappings valid past the unlink.

bfloat16 rides as a raw uint16 view (multiprocessing.shared_memory is
dtype-agnostic; ml_dtypes restores the view on rebuild).
"""
from __future__ import annotations

import atexit
from multiprocessing.reduction import ForkingPickler
from multiprocessing import shared_memory

import numpy as np

from ..._core.tensor import Tensor

__all__ = ["init_reductions"]

# sender-side keepalive: a pickle payload can sit in a Queue long after
# the source tensor is gone, and unlinking before every receiver has
# mapped breaks the rebuild (FileNotFoundError). The SENDER holds each
# block in an LRU bounded by total bytes (reference: reductions.py's
# _LRUSharedCache bounds the same lifetime problem) — beyond the
# window the oldest blocks are unlinked, so a long-running producer
# cannot fill /dev/shm; an undelivered payload older than the window
# fails to rebuild, the same contract as the reference cache. The
# atexit sweep unlinks the remainder at exit.
import threading
from collections import OrderedDict

_sent_blocks = OrderedDict()
_sent_bytes = [0]
# mp.Queue serializes on its FEEDER thread, so two queues in one
# process reduce concurrently — the cache accounting needs a lock
_sent_lock = threading.Lock()
_SHM_BYTES_CAP = int(__import__("os").environ.get(
    "PT_MP_SHM_BYTES", str(1 << 30)))


def _evict_over_cap_locked():
    while _sent_bytes[0] > _SHM_BYTES_CAP and len(_sent_blocks) > 1:
        name = next(iter(_sent_blocks))
        _release_locked(name)


def _cleanup_all():
    with _sent_lock:
        for name in list(_sent_blocks):
            _release_locked(name)


atexit.register(_cleanup_all)


def _untrack(name):
    """Drop a receiver-side resource_tracker claim (attach registers,
    cpython bpo-39959): the sender's unlink() is the one true
    unregister. Cost of the sender-owned lifetime: a SIGKILLed sender
    leaks its blocks until reboot — the same profile as the
    reference's file_system strategy."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(name, "shared_memory")
    except Exception:
        pass


def _np_view(arr):
    """Byte-level host view: transports ANY dtype (bf16, float8, ...)
    as raw uint8 bytes; the logical (shape, dtype name) ride in the
    payload and the view is re-applied at rebuild."""
    a = np.ascontiguousarray(np.atleast_1d(np.asarray(arr)))
    return a.view(np.uint8), str(np.asarray(arr).dtype)


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _rebuild_tensor(shm_name, shape, dtype_name, stop_gradient):
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        if shm_name not in _sent_blocks:
            # cross-process receiver: attach registered the block in
            # THIS process's tracker, but lifetime belongs to the
            # sender (whose unlink() unregisters in ITS tracker) —
            # drop the bogus claim or this process warns 'leaked' at
            # shutdown. An in-process rebuild keeps the entry: it IS
            # the sender's, and unlink() unregisters it exactly once.
            _untrack(shm._name)
        dt = _np_dtype(dtype_name)
        nbytes = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        base = np.ndarray((max(1, nbytes),), dtype=np.uint8,
                          buffer=shm.buf)[:nbytes]
        # one copy out of the mapping: the tensor owns its memory and
        # the sender remains free to unlink (a jax device_put would
        # copy anyway)
        arr = np.array(base).view(dt).reshape(shape)
    finally:
        shm.close()
    t = Tensor(arr)
    t.stop_gradient = stop_gradient
    return t


def _reduce_tensor(tensor):
    host, dtype_name = _np_view(tensor.numpy())
    shm = shared_memory.SharedMemory(create=True, size=max(1, host.nbytes))
    view = np.ndarray(host.shape, dtype=np.uint8, buffer=shm.buf)
    view[...] = host
    with _sent_lock:
        _sent_blocks[shm.name] = shm
        _sent_bytes[0] += shm.size
        _evict_over_cap_locked()
    return (_rebuild_tensor,
            (shm.name, tuple(tensor.shape), dtype_name,
             bool(tensor.stop_gradient)))


def _rebuild_parameter(shm_name, shape, dtype_name, attrs):
    t = _rebuild_tensor(shm_name, shape, dtype_name,
                        stop_gradient=not attrs["trainable"])
    from ..._core.tensor import Parameter
    p = Parameter(t._value, name=attrs["name"],
                  trainable=attrs["trainable"])
    p.optimize_attr = attrs["optimize_attr"]
    p.need_clip = attrs["need_clip"]
    p.is_distributed = attrs["is_distributed"]
    return p


def _reduce_parameter(param):
    """A Parameter must cross AS a Parameter: trainable/optimize_attr/
    need_clip feed optimizers and clip on the receiving side (the
    regularizer object does not cross — it may hold arbitrary
    callables; the reference ships metadata only, same contract)."""
    fn, (name, shape, dtype_name, _) = _reduce_tensor(param)
    attrs = {"trainable": bool(param.trainable),
             "optimize_attr": dict(param.optimize_attr or {}),
             "need_clip": bool(param.need_clip),
             "is_distributed": bool(param.is_distributed),
             "name": getattr(param, "name", None)}
    return (_rebuild_parameter, (name, shape, dtype_name, attrs))


def _release(name):
    with _sent_lock:
        _release_locked(name)


def _release_locked(name):
    shm = _sent_blocks.pop(name, None)
    if shm is not None:
        _sent_bytes[0] -= shm.size
        try:
            # forkserver children can SHARE the parent's tracker; a
            # receiver's untrack then removed OUR entry from the shared
            # set and unlink()'s unregister would KeyError-spam the
            # tracker. Re-register first: no-op when the entry exists
            # (set semantics), restores it when a receiver dropped it.
            from multiprocessing import resource_tracker
            resource_tracker.register(shm._name, "shared_memory")
        except Exception:
            pass
        try:
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


def init_reductions():
    """reference reductions.py:243 — register the Tensor reducers on
    ForkingPickler so mp.Queue/Pipe move tensors through shared
    memory instead of pickling the bytes."""
    ForkingPickler.register(Tensor, _reduce_tensor)
    from ..._core.tensor import Parameter
    ForkingPickler.register(Parameter, _reduce_parameter)
