"""incubate.nn fused layers/functionals (reference: python/paddle/incubate/
nn/{layer,functional}).

On TPU "fused" means: expressed as one XLA graph (fusion by compiler) or
a pallas kernel (attention). These wrappers match the reference call
signatures over our kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply
from ...nn.layer.layers import Layer
from ...nn import functional as NF
from ...ops import fused as _fused
from ...ops.flash_attention import flash_attention as _flash
from ...ops.rope import rope_cos_sin, apply_rotary_emb


class functional:
    @staticmethod
    def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                                   pre_ln_scale=None, pre_ln_bias=None,
                                   ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                                   qkv_bias=None, linear_bias=None, cache_kv=None,
                                   attn_mask=None, dropout_rate=0.0,
                                   attn_dropout_rate=0.0, ln_epsilon=1e-5,
                                   training=True, num_heads=None, **kw):
        def fn(xr, qkv_w, lin_w, *rest):
            rest = list(rest)
            qkv_b = rest.pop(0) if qkv_bias is not None else None
            lin_b = rest.pop(0) if linear_bias is not None else None
            b, s, d = xr.shape
            # qkv_w: (3, H, Dh, D) reference layout
            three, h, dh, _ = qkv_w.shape
            qkv = jnp.einsum("bsd,thed->bsthe", xr, qkv_w)
            if qkv_b is not None:
                qkv = qkv + qkv_b[None, None]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            out, _ = _flash(q, k, v, dropout=attn_dropout_rate, causal=False,
                            training=training)
            out = out.reshape(b, s, h * dh)
            out = out @ lin_w
            if lin_b is not None:
                out = out + lin_b
            return out
        args = [x, qkv_weight, linear_weight]
        if qkv_bias is not None:
            args.append(qkv_bias)
        if linear_bias is not None:
            args.append(linear_bias)
        return apply(fn, *args, name="fused_multi_head_attention")

    @staticmethod
    def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                          linear2_bias=None, ln1_scale=None, ln1_bias=None,
                          ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                          dropout2_rate=0.5, activation="relu",
                          ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                          pre_layer_norm=False, training=True, **kw):
        def fn(xr, w1, w2, *rest):
            rest = list(rest)
            b1 = rest.pop(0) if linear1_bias is not None else None
            b2 = rest.pop(0) if linear2_bias is not None else None
            h = xr @ w1
            if b1 is not None:
                h = h + b1
            h = getattr(jax.nn, activation)(h) if hasattr(jax.nn, activation) \
                else jax.nn.relu(h)
            out = h @ w2
            if b2 is not None:
                out = out + b2
            return xr + out
        args = [x, linear1_weight, linear2_weight]
        if linear1_bias is not None:
            args.append(linear1_bias)
        if linear2_bias is not None:
            args.append(linear2_bias)
        return apply(fn, *args, name="fused_feedforward")

    @staticmethod
    def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                       begin_norm_axis=-1, **kw):
        def fn(a, w):
            return _fused.fused_rms_norm(a, w, epsilon)
        return apply(fn, x, norm_weight, name="fused_rms_norm")

    @staticmethod
    def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, **kw):
        return NF.layer_norm(x, [x.shape[-1]], norm_weight, norm_bias, epsilon)

    @staticmethod
    def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                        position_ids=None, use_neox_rotary_style=True,
                                        **kw):
        def fn(qr, kr, c, s):
            qo, ko = apply_rotary_emb(qr, kr, c, s)
            return qo, ko
        out = apply(fn, q, k, cos, sin, name="fused_rope", multi=True)
        return (out[0], out[1], v)

    @staticmethod
    def fused_linear(x, weight, bias=None, transpose_weight=False):
        if transpose_weight:
            from ...tensor.linalg import matmul
            out = matmul(x, weight, transpose_y=True)
            if bias is not None:
                out = out + bias
            return out
        return NF.linear(x, weight, bias)

    @staticmethod
    def fused_linear_cross_entropy(x, weight, labels, bias=None,
                                   chunk_size=8192, reduction="mean",
                                   ignore_index=-100, name=None):
        """CE over x@weight without materializing (N, V) logits — the
        LLM-vocab memory optimization (chunked online logsumexp fwd,
        per-chunk softmax recompute bwd)."""
        def fn(xr, w, lab, *rest):
            b = rest[0] if rest else None
            return _fused.fused_linear_cross_entropy(
                xr.reshape(-1, xr.shape[-1]), w, lab.reshape(-1), bias=b,
                chunk_size=chunk_size, reduction=reduction,
                ignore_index=ignore_index)
        args = (x, weight, labels) + ((bias,) if bias is not None else ())
        return apply(fn, *args, name="fused_linear_cross_entropy")

    @staticmethod
    def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                          name=None):
        from ..._core.state import prng
        key = prng.next_key()
        return apply(lambda a, b: _fused.fused_dropout_add(a, b, p, key, training),
                     x, y, name="fused_dropout_add")

    @staticmethod
    def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                               ln_scale=None, ln_bias=None,
                                               dropout_rate=0.5, ln_epsilon=1e-5,
                                               training=True, **kw):
        h = x if bias is None else x + bias
        h = NF.dropout(h, dropout_rate, training=training)
        h = h + residual
        return NF.layer_norm(h, [h.shape[-1]], ln_scale, ln_bias, ln_epsilon)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, **kw):
        super().__init__()
        from ...nn.layer.transformer import MultiHeadAttention
        self.inner = MultiHeadAttention(embed_dim, num_heads,
                                        dropout=attn_dropout_rate)
        self.dropout_rate = dropout_rate

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return self.inner(query, key, value, attn_mask, cache)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 **kw):
        super().__init__()
        from ...nn.layer.common import Linear, Dropout
        from ...nn.layer.norm import LayerNorm
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(act_dropout_rate if act_dropout_rate is not None
                               else dropout_rate)
        self.activation = activation
        self.normalize_before = normalize_before

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.linear2(self.dropout(
            getattr(NF, self.activation)(self.linear1(src))))
        out = residual + src
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, **kw):
        super().__init__()
        from ...nn.layer.transformer import TransformerEncoderLayer
        self.inner = TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout_rate, activation,
            attn_dropout_rate, act_dropout_rate, normalize_before)

    def forward(self, src, src_mask=None):
        return self.inner(src, src_mask)


from ...parallel.moe import MoELayer as FusedMoE  # noqa: E402

flash_attention = _flash

# rebind `functional` from the legacy class to the real submodule (same
# surface + the full fused-op set); plain `from . import functional`
# would NOT import it here — the class already occupies the attribute
import paddle_tpu.incubate.nn.functional as _functional_mod  # noqa: E402

functional = _functional_mod


class FusedLinear(Layer):
    """reference: incubate.nn.FusedLinear — matmul+bias in one kernel
    (XLA fuses it; kept for API parity). transpose_weight stores the
    weight as [out, in] (reference checkpoint layout) and transposes in
    the fused matmul."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        from ...nn.layer.common import Linear
        self.transpose_weight = transpose_weight
        if transpose_weight:
            from ...nn.initializer import XavierUniform, Constant
            self.weight = self.create_parameter(
                [out_features, in_features], attr=weight_attr,
                default_initializer=XavierUniform())
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True,
                default_initializer=Constant(0.0))
            self._linear = None
        else:
            self._linear = Linear(in_features, out_features,
                                  weight_attr=weight_attr,
                                  bias_attr=bias_attr)
            self.weight = self._linear.weight
            self.bias = self._linear.bias

    def forward(self, x):
        if self._linear is not None:
            return self._linear(x)
        return _functional_mod.fused_linear(x, self.weight, self.bias,
                                            transpose_weight=True)


class FusedDropoutAdd(Layer):
    """reference: incubate.nn.FusedDropoutAdd — dropout(x) + y."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return _functional_mod.fused_dropout_add(
            x, y, p=self.p, training=self.training, mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: incubate.nn.FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        return _functional_mod.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedMultiTransformer(Layer):
    """reference: incubate.nn.FusedMultiTransformer — the whole pre-LN
    decoder stack as one fused call (see functional.fused_multi_transformer)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5, **kw):
        super().__init__()
        from ...nn.initializer import Constant, XavierUniform
        self.num_layers = num_layers
        self.activation = activation
        self.epsilon = epsilon
        self.dropout_rate = dropout_rate
        head_dim = embed_dim // num_heads
        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            def mk(shape, init=None, bias=False):
                return self.create_parameter(
                    shape, is_bias=bias,
                    default_initializer=init or XavierUniform())
            one, zero = Constant(1.0), Constant(0.0)
            self.ln_scales.append(mk([embed_dim], one))
            self.ln_biases.append(mk([embed_dim], zero, True))
            self.qkv_weights.append(mk([3, num_heads, head_dim, embed_dim]))
            self.qkv_biases.append(mk([3, num_heads, head_dim], zero, True))
            self.linear_weights.append(mk([embed_dim, embed_dim]))
            self.linear_biases.append(mk([embed_dim], zero, True))
            self.ffn_ln_scales.append(mk([embed_dim], one))
            self.ffn_ln_biases.append(mk([embed_dim], zero, True))
            self.ffn1_weights.append(mk([embed_dim, dim_feedforward]))
            self.ffn1_biases.append(mk([dim_feedforward], zero, True))
            self.ffn2_weights.append(mk([dim_feedforward, embed_dim]))
            self.ffn2_biases.append(mk([embed_dim], zero, True))
            for nm, lst in [("ln_s", self.ln_scales), ("ln_b", self.ln_biases),
                            ("qkv_w", self.qkv_weights), ("qkv_b", self.qkv_biases),
                            ("lin_w", self.linear_weights), ("lin_b", self.linear_biases),
                            ("fln_s", self.ffn_ln_scales), ("fln_b", self.ffn_ln_biases),
                            ("f1_w", self.ffn1_weights), ("f1_b", self.ffn1_biases),
                            ("f2_w", self.ffn2_weights), ("f2_b", self.ffn2_biases)]:
                self.add_parameter(f"{nm}_{i}", lst[-1])

    def forward(self, x, attn_mask=None, caches=None, **kw):
        return _functional_mod.fused_multi_transformer(
            x, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            epsilon=self.epsilon, dropout_rate=self.dropout_rate,
            activation=self.activation,
            training=self.training, cache_kvs=caches, attn_mask=attn_mask)


# xformers-style memory-efficient attention. SUBMODULE bindings only —
# re-exporting the function would shadow the module and break the
# reference-style `paddle.incubate.nn.memory_efficient_attention.
# memory_efficient_attention(...)` access path.
from . import attn_bias  # noqa: E402,F401
from . import memory_efficient_attention  # noqa: E402,F401
