"""xformers-style attention-bias types (reference:
python/paddle/incubate/nn/attn_bias.py — itself the xformers
AttentionBias hierarchy). These describe STRUCTURED masks so
memory_efficient_attention can route each to the right TPU kernel
instead of materializing an O(S^2) bias:

  * LowerTriangularMask            -> causal flash kernel
  * BlockDiagonal(Causal)Mask      -> varlen segment-id pallas kernel
  * *WithTensorBias / padded-keys  -> XLA path with the materialized mask

materialize() is provided for every type (it IS the spec of the mask),
built functionally from interval/segment comparisons — no in-place
slice writes, so it traces under jit.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..._core.tensor import Tensor, unwrap

__all__ = [
    "AttentionBias",
    "LowerTriangularMask",
    "LowerTriangularMaskWithTensorBias",
    "SeqLenInfo",
    "PaddedSeqLenInfo",
    "BlockDiagonalMask",
    "BlockDiagonalCausalMask",
    "BlockDiagonalCausalWithOffsetPaddedKeysMask",
]

_NEG_INF = float("-inf")


def _as_np_dtype(dtype):
    if str(dtype) == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.dtype(str(dtype))


def _finish(mask_2d, shape, dtype):
    """Broadcast a (Sq, Sk) mask to the requested shape as a Tensor."""
    m = jnp.asarray(mask_2d, _as_np_dtype(dtype))
    for _ in range(len(shape) - 2):
        m = m[None]
    return Tensor(jnp.broadcast_to(m, tuple(shape)))


class AttentionBias(ABC):
    @abstractmethod
    def materialize(self, shape, dtype="float32"):
        """Additive bias tensor of `shape` (0 where attending is allowed,
        -inf where blocked)."""


class LowerTriangularMask(AttentionBias):
    def materialize(self, shape, dtype="float32"):
        sq, sk = shape[-2], shape[-1]
        m = np.where(np.tril(np.ones((sq, sk), bool)), 0.0, _NEG_INF)
        return _finish(m.astype(np.float32), shape, dtype)

    def add_bias(self, bias):
        return LowerTriangularMaskWithTensorBias(bias)


class LowerTriangularMaskWithTensorBias(LowerTriangularMask):
    def __init__(self, bias):
        self._bias = bias

    def materialize(self, shape, dtype="float32"):
        base = unwrap(super().materialize(shape, dtype))
        return Tensor(base + jnp.asarray(unwrap(self._bias),
                                         base.dtype))


@dataclass
class SeqLenInfo:
    """Prefix-sum description of packed sequences (xformers SeqLenInfo):
    seqstart[i] is the token offset where sequence i begins."""
    seqstart: Tensor
    max_seqlen: int
    seqstart_py: list

    def intervals(self):
        yield from zip(self.seqstart_py, self.seqstart_py[1:])

    @classmethod
    def from_seqlens(cls, seqlens):
        seqstart_py = [0]
        max_seqlen = -1
        for s in seqlens:
            max_seqlen = max(max_seqlen, int(s))
            seqstart_py.append(seqstart_py[-1] + int(s))
        return cls(seqstart=Tensor(jnp.asarray(seqstart_py, jnp.int32)),
                   max_seqlen=max_seqlen, seqstart_py=seqstart_py)

    def seg_ids(self):
        """(total,) int32 segment id per packed token — the varlen
        kernel's native mask representation."""
        lens = np.diff(self.seqstart_py)
        return np.repeat(np.arange(len(lens)), lens).astype(np.int32)

    def split(self, x, batch_sizes=None):
        assert self.seqstart_py[-1] == x.shape[1] and x.shape[0] == 1, \
            "split expects the packed (1, total, ...) layout"
        if batch_sizes is None:
            batch_sizes = [1] * (len(self.seqstart_py) - 1)
        raw = unwrap(x)
        out, it = [], 0
        for bs in batch_sizes:
            start = self.seqstart_py[it]
            stop = self.seqstart_py[it + bs]
            chunk = raw[:, start:stop]
            out.append(Tensor(chunk.reshape(bs, -1, *chunk.shape[2:])))
            it += bs
        return out


@dataclass
class PaddedSeqLenInfo(SeqLenInfo):
    """Blocks padded to a fixed stride; seqlen holds each block's ACTUAL
    length (serving KV-page layout)."""
    seqlen: Optional[Tensor] = None
    seqlen_py: Sequence = ()

    def intervals(self):
        for (start, _), length in zip(
                zip(self.seqstart_py, self.seqstart_py[1:]),
                self.seqlen_py):
            yield start, start + int(length)

    @classmethod
    def from_seqlens(cls, seqlens):
        raise NotImplementedError(
            "use SeqLenInfo.from_seqlens or "
            "PaddedSeqLenInfo.from_seqlens_padded")

    @classmethod
    def from_seqlens_padded(cls, seqlens, padding):
        assert all(int(s) <= padding for s in seqlens)
        seqstart_py = list(range(0, len(seqlens) * padding + 1, padding))
        return cls(seqstart=Tensor(jnp.asarray(seqstart_py, jnp.int32)),
                   max_seqlen=max(int(s) for s in seqlens),
                   seqstart_py=seqstart_py,
                   seqlen=Tensor(jnp.asarray(list(seqlens), jnp.int32)),
                   seqlen_py=list(seqlens))

    def split(self, x, batch_sizes=None):
        raise NotImplementedError


@dataclass
class BlockDiagonalMask(AttentionBias):
    q_seqinfo: SeqLenInfo
    k_seqinfo: SeqLenInfo
    _batch_sizes: Optional[Sequence] = None

    _causal = False

    def materialize(self, shape, dtype="float32"):
        assert shape[-1] == self.k_seqinfo.seqstart_py[-1]
        assert shape[-2] == self.q_seqinfo.seqstart_py[-1]
        # segment-id comparison instead of per-block slice writes
        seg_q = self.q_seqinfo.seg_ids()
        seg_k = self.k_seqinfo.seg_ids()
        allowed = seg_q[:, None] == seg_k[None, :]
        if self._causal:
            # within-block causal: position inside own sequence
            pos_q = np.arange(len(seg_q)) - np.asarray(
                self.q_seqinfo.seqstart_py)[seg_q]
            pos_k = np.arange(len(seg_k)) - np.asarray(
                self.k_seqinfo.seqstart_py)[seg_k]
            allowed &= pos_k[None, :] <= pos_q[:, None]
        m = np.where(allowed, 0.0, _NEG_INF).astype(np.float32)
        return _finish(m, shape, dtype)

    @classmethod
    def from_seqlens(cls, q_seqlen, kv_seqlen=None):
        assert kv_seqlen is None or len(q_seqlen) == len(kv_seqlen)
        q_seqinfo = SeqLenInfo.from_seqlens(q_seqlen)
        if kv_seqlen is None or list(q_seqlen) == list(kv_seqlen):
            k_seqinfo = q_seqinfo
        else:
            k_seqinfo = SeqLenInfo.from_seqlens(kv_seqlen)
        return cls(q_seqinfo=q_seqinfo, k_seqinfo=k_seqinfo)

    @classmethod
    def from_tensor_list(cls, tensors):
        batch_sizes = [t.shape[0] for t in tensors]
        seqlens = []
        for x in tensors:
            seqlens.extend([x.shape[1]] * x.shape[0])
        block = cls.from_seqlens(seqlens)
        block._batch_sizes = batch_sizes
        packed = jnp.concatenate(
            [unwrap(x).reshape(1, -1, *x.shape[2:]) for x in tensors],
            axis=1)
        return block, Tensor(packed)

    @classmethod
    def from_tensor_lists_qkv(cls, tensors_q, tensors_k, tensors_v=None):
        assert len(tensors_q) == len(tensors_k)
        q_seqlens, kv_seqlens = [], []
        for q, k in zip(tensors_q, tensors_k):
            assert q.shape[0] == k.shape[0]
            q_seqlens.extend([q.shape[1]] * q.shape[0])
            kv_seqlens.extend([k.shape[1]] * k.shape[0])
        block = cls.from_seqlens(q_seqlens, kv_seqlens)
        block._batch_sizes = [x.shape[0] for x in tensors_q]

        def pack(ts):
            return Tensor(jnp.concatenate(
                [unwrap(x).reshape(1, -1, *x.shape[2:]) for x in ts],
                axis=1))

        return (block, pack(tensors_q), pack(tensors_k),
                pack(tensors_v) if tensors_v is not None else None)

    def split_queries(self, tensor):
        return self.q_seqinfo.split(tensor, self._batch_sizes)

    def split_kv(self, tensor):
        return self.k_seqinfo.split(tensor, self._batch_sizes)

    def split(self, tensor):
        assert self.q_seqinfo is self.k_seqinfo
        return self.q_seqinfo.split(tensor, self._batch_sizes)

    def make_causal(self):
        return BlockDiagonalCausalMask(q_seqinfo=self.q_seqinfo,
                                       k_seqinfo=self.k_seqinfo,
                                       _batch_sizes=self._batch_sizes)


@dataclass
class BlockDiagonalCausalMask(BlockDiagonalMask):
    _causal = True


@dataclass
class BlockDiagonalCausalWithOffsetPaddedKeysMask(AttentionBias):
    """Per-block causal attention against PADDED key pages whose real
    lengths live in k_seqinfo.seqlen — the serving decode/verify layout
    (the paged-attention kernel serves the compiled engine; this type
    is the eager/offline spec of the same mask)."""
    q_seqinfo: SeqLenInfo
    k_seqinfo: PaddedSeqLenInfo
    causal_diagonal: Optional[Tensor] = None

    @classmethod
    def from_seqlens(cls, q_seqlen, kv_padding, kv_seqlen,
                     causal_diagonal=None):
        """reference attn_bias.py:265 — the canonical constructor."""
        assert kv_seqlen is None or len(q_seqlen) == len(kv_seqlen)
        return cls(q_seqinfo=SeqLenInfo.from_seqlens(q_seqlen),
                   k_seqinfo=PaddedSeqLenInfo.from_seqlens_padded(
                       kv_seqlen, kv_padding),
                   causal_diagonal=causal_diagonal)

    def materialize(self, shape, dtype="float32"):
        assert shape[-1] == self.k_seqinfo.seqstart_py[-1]
        assert shape[-2] == self.q_seqinfo.seqstart_py[-1]
        tq = self.q_seqinfo.seqstart_py[-1]
        tk = self.k_seqinfo.seqstart_py[-1]
        m = np.full((tq, tk), _NEG_INF, np.float32)
        diag = (np.asarray(unwrap(self.causal_diagonal)).tolist()
                if self.causal_diagonal is not None else None)
        for i, ((qs, qe), (ks, ke)) in enumerate(zip(
                self.q_seqinfo.intervals(), self.k_seqinfo.intervals())):
            nq, nk = qe - qs, ke - ks
            off = int(diag[i]) if diag is not None else 0
            # reference semantics: triu(full(-inf), diagonal=1+off) —
            # allowed (0) where k - q <= off, TOP-left aligned
            block = np.where(np.tril(np.ones((nq, nk), bool), k=off),
                             0.0, _NEG_INF)
            m[qs:qe, ks:ke] = block
        return _finish(m, shape, dtype)
