"""paddle.incubate.nn.functional as a real module (reference:
python/paddle/incubate/nn/functional/__init__.py — ~20 fused CUDA ops).

TPU mapping: the "fused" ops are either XLA-fused elementwise chains (XLA
does the fusion the CUDA kernels hand-code) or route to the pallas
kernels in ops/ (flash, paged, varlen attention). The class-style
``incubate.nn.functional`` accessor from earlier rounds keeps working;
this module is the importable form (``import
paddle_tpu.incubate.nn.functional as F``).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap

# The earlier rounds shipped these as staticmethods on a `functional`
# class inside the package __init__ (attribute-access style). The parent
# package is fully executed before this submodule, so lift them off the
# class here; the parent then rebinds `functional` to this module, which
# exposes the same names — both access styles keep working.
import sys as _sys

_cls = getattr(_sys.modules[__package__], "functional")
fused_multi_head_attention = _cls.fused_multi_head_attention
fused_feedforward = _cls.fused_feedforward
fused_rms_norm = _cls.fused_rms_norm
fused_layer_norm = _cls.fused_layer_norm
fused_rotary_position_embedding = _cls.fused_rotary_position_embedding
fused_linear = _cls.fused_linear
fused_linear_cross_entropy = _cls.fused_linear_cross_entropy

__all__ = [
    "fused_multi_head_attention", "fused_feedforward", "fused_rms_norm",
    "fused_layer_norm", "fused_rotary_position_embedding", "fused_linear",
    "fused_linear_cross_entropy", "swiglu", "fused_dropout_add",
    "fused_bias_act", "fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
    "masked_multihead_attention", "block_multihead_attention",
    "variable_length_memory_efficient_attention",
    "fused_dot_product_attention", "moe_dispatch", "moe_ffn", "moe_reduce",
    "fused_moe", "blha_get_max_len", "fused_linear_activation",
    "fused_multi_transformer",
]


def swiglu(x, y=None, name=None):
    """reference: incubate/nn/functional/swiglu.py — silu(x) * y (y
    defaults to the second half of x's last axis)."""
    def fn(a, *rest):
        if rest:
            b = rest[0]
        else:
            a, b = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a) * b
    args = [x] + ([y] if y is not None else [])
    return apply(fn, *args, name="swiglu")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """reference: fused_dropout_add — dropout(x) + y in one pass."""
    from ..._core.state import prng
    if not training or p == 0.0:
        return apply(lambda a, b: a + b, x, y, name="fused_dropout_add")
    key = prng.next_key()

    def fn(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0) + b
        return jnp.where(keep, a, 0.0) + b
    return apply(fn, x, y, name="fused_dropout_add")


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0, name=None):
    """reference: fused_bias_act — (x + bias) then activation."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "swiglu": lambda v: jax.nn.silu(*jnp.split(v, 2, -1)[:1]) *
           jnp.split(v, 2, -1)[1],
           "geglu": lambda v: jax.nn.gelu(jnp.split(v, 2, -1)[0]) *
           jnp.split(v, 2, -1)[1]}[act_method]

    def fn(a, *rest):
        if rest:
            a = a + rest[0]
        return act(a)
    args = [x] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="fused_bias_act")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="fused_matmul_bias")


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """reference: fused_bias_dropout_residual_layer_norm."""
    h = fused_dropout_add(x if bias is None else
                          apply(lambda a, b: a + b, x, bias), residual,
                          p=dropout_rate, training=training, mode=mode)

    def fn(a, *rest):
        mu = jnp.mean(a, -1, keepdims=True)
        var = jnp.var(a, -1, keepdims=True)
        out = (a - mu) * jax.lax.rsqrt(var + ln_epsilon)
        i = 0
        if ln_scale is not None:
            out = out * rest[i]
            i += 1
        if ln_bias is not None:
            out = out + rest[i]
        return out
    args = [h] + ([ln_scale] if ln_scale is not None else []) + \
        ([ln_bias] if ln_bias is not None else [])
    return apply(fn, *args, name="fused_bias_dropout_residual_ln")


def fused_dot_product_attention(q, k, v, attn_mask=None, scaling_factor=None,
                                dropout_probability=0.0, is_training=True,
                                is_causal_masking=False, name=None):
    """reference: fused_dot_product_attention (cuDNN) → flash kernel.
    q/k/v: (B, S, H, D)."""
    from ...ops.flash_attention import flash_attention as _flash

    def fn(qq, kk, vv):
        out, _ = _flash(qq, kk, vv, dropout=dropout_probability,
                        causal=is_causal_masking, training=is_training,
                        sm_scale=scaling_factor)
        return out
    return apply(fn, q, k, v, name="fused_dot_product_attention")


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """reference: variable_length_memory_efficient_attention — ragged
    batch attention; maps to the varlen pallas kernel via cu_seqlens.
    query: (B, H, S, D) with per-batch valid lengths seq_lens."""
    from ...ops.varlen_attention import flash_attn_unpadded as _varlen
    qv, kv_, vv = unwrap(query), unwrap(key), unwrap(value)
    lens_q = np.asarray(unwrap(seq_lens)).reshape(-1)
    lens_k = np.asarray(unwrap(kv_seq_lens)).reshape(-1)
    b, h, s, d = qv.shape
    sk = kv_.shape[2]
    # pack valid tokens
    packs_q = [np.asarray(qv[i, :, :lens_q[i]]).transpose(1, 0, 2)
               for i in range(b)]
    packs_k = [np.asarray(kv_[i, :, :lens_k[i]]).transpose(1, 0, 2)
               for i in range(b)]
    packs_v = [np.asarray(vv[i, :, :lens_k[i]]).transpose(1, 0, 2)
               for i in range(b)]
    cu_q = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    cu_k = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    out, _ = _varlen(jnp.asarray(np.concatenate(packs_q)),
                     jnp.asarray(np.concatenate(packs_k)),
                     jnp.asarray(np.concatenate(packs_v)),
                     jnp.asarray(cu_q), jnp.asarray(cu_k),
                     scale=scale, causal=causal)
    out = np.asarray(out)
    res = np.zeros((b, h, s, d), out.dtype)
    for i in range(b):
        res[i, :, :lens_q[i]] = out[cu_q[i]:cu_q[i + 1]].transpose(1, 0, 2)
    return Tensor(jnp.asarray(res))


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", **kwargs):
    """reference: masked_multihead_attention — single-token decode over a
    dense (2, B, H, S, D) cache (the paged path is ops/paged_attention)."""
    xv = unwrap(x)
    cache = unwrap(cache_kv)
    b = xv.shape[0]
    _, _, h, s_max, d = cache.shape
    q, k, v = jnp.split(xv.reshape(b, 3, h, d), 3, axis=1)
    q, k, v = q[:, 0], k[:, 0], v[:, 0]
    lens = unwrap(sequence_lengths) if sequence_lengths is not None else \
        jnp.zeros((b,), jnp.int32)
    pos = lens.reshape(b)
    ck, cv = cache[0], cache[1]
    ck = ck.at[jnp.arange(b), :, pos].set(k)
    cv = cv.at[jnp.arange(b), :, pos].set(v)
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                        ck.astype(jnp.float32)) * scale
    mask = jnp.arange(s_max)[None, None, :] <= pos[:, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bhsd->bhd", p, cv.astype(jnp.float32))
    new_cache = jnp.stack([ck, cv])
    return (Tensor(out.reshape(b, h * d).astype(xv.dtype)),
            Tensor(new_cache))


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, **kwargs):
    """reference: block_multihead_attention (PaddleNLP serving core) —
    the paged-KV decode step. qkv packs (num_head + 2*kv_heads) heads per
    token: the query heads first, then this token's K and V heads, which
    are scattered into the paged pools at each sequence's current length
    before attending. See models/llama_serving.py for the full engine
    (continuous batching, varlen prefill)."""
    from ...ops.paged_attention import paged_attention
    kc = unwrap(key_cache)
    vc = unwrap(value_cache)
    kvh, num_pages, page_size, d = kc.shape
    q3 = unwrap(qkv)
    b = q3.shape[0]
    q3 = q3.reshape(b, -1, d)
    h = q3.shape[1] - 2 * kvh
    if h <= 0:
        raise ValueError(
            f"qkv packs {q3.shape[1]} heads but caches have {kvh} kv heads "
            f"— expected num_head + 2*{kvh}")
    q, k_new, v_new = q3[:, :h], q3[:, h:h + kvh], q3[:, h + kvh:]
    lens = unwrap(seq_lens_decoder).reshape(-1).astype(jnp.int32)
    tables = unwrap(block_tables).astype(jnp.int32)
    # scatter this token's K/V: page = table[b, len//page], slot = len%page
    bidx = jnp.arange(b)
    pages = tables[bidx, lens // page_size]
    slots = lens % page_size
    kc = kc.at[:, pages, slots].set(jnp.swapaxes(k_new, 0, 1))
    vc = vc.at[:, pages, slots].set(jnp.swapaxes(v_new, 0, 1))
    out = paged_attention(q, kc, vc, tables, lens + 1)
    return Tensor(out), Tensor(kc), Tensor(vc)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None):
    """reference: blha_get_max_len — max enc/dec lengths for kernel
    dispatch."""
    e = unwrap(seq_lens_encoder)
    d = unwrap(seq_lens_decoder)
    return Tensor(jnp.max(e)), Tensor(jnp.max(d))


# ------------------------------------------------------------------- MoE
def moe_dispatch(x, gating_logits, moe_topk, group_moe=False,
                 topk_only_mode=False):
    """reference: fused_moe moe_dispatch — top-k routing tables."""
    xv = unwrap(x)
    logits = unwrap(gating_logits)
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    topv, topi = jax.lax.top_k(probs, moe_topk)
    n_exp = logits.shape[-1]
    # rows sorted by expert id → permuted input table
    flat_exp = topi.reshape(-1)
    order = jnp.argsort(flat_exp, stable=True)
    token_ids = jnp.repeat(jnp.arange(xv.shape[0]), moe_topk)[order]
    permuted = xv[token_ids]
    rows_per_exp = jnp.sum(jax.nn.one_hot(flat_exp, n_exp, dtype=jnp.int32),
                           axis=0)
    return (Tensor(permuted), Tensor(token_ids.astype(jnp.int32)),
            Tensor(order.astype(jnp.int32)), Tensor(rows_per_exp),
            Tensor(topv))


def moe_ffn(permuted_x, rows_per_expert, up_gate_weight, down_weight,
            up_gate_bias=None, down_bias=None, quant_method="None"):
    """Apply each expert's FFN to its contiguous row block."""
    xv = unwrap(permuted_x)
    counts = np.asarray(unwrap(rows_per_expert))
    ug = unwrap(up_gate_weight)
    dw = unwrap(down_weight)
    ugb = unwrap(up_gate_bias) if up_gate_bias is not None else None
    dwb = unwrap(down_bias) if down_bias is not None else None
    outs = []
    start = 0
    for e, n in enumerate(counts):
        blk = xv[start:start + int(n)]
        hgate = blk @ ug[e]
        if ugb is not None:
            hgate = hgate + ugb[e]
        a, b = jnp.split(hgate, 2, -1)
        h = jax.nn.silu(a) * b
        y = h @ dw[e]
        if dwb is not None:
            y = y + dwb[e]
        outs.append(y)
        start += int(n)
    return Tensor(jnp.concatenate(outs, 0) if outs else xv[:0])


def moe_reduce(ffn_out, topk_weights, permute_indices_per_token,
               token_ids, norm_topk_prob=True, routed_scaling_factor=1.0):
    """Scatter expert outputs back to token order and combine by gate."""
    y = unwrap(ffn_out)
    order = unwrap(permute_indices_per_token).astype(jnp.int32)
    tok = unwrap(token_ids).astype(jnp.int32)
    w = unwrap(topk_weights)
    n_tok, k = w.shape
    # invert the dispatch permutation: row r came from (token tok[r],
    # slot order[r] % k)
    unperm = jnp.zeros((n_tok * k, y.shape[-1]), y.dtype)
    unperm = unperm.at[order].set(y)
    unperm = unperm.reshape(n_tok, k, -1)
    ww = w / jnp.sum(w, -1, keepdims=True) if norm_topk_prob else w
    out = jnp.einsum("tkd,tk->td", unperm.astype(jnp.float32),
                     ww.astype(jnp.float32)) * routed_scaling_factor
    return Tensor(out.astype(y.dtype))


def fused_moe(x, gate_weight, up_gate_weight, down_weight, moe_topk=2,
              norm_topk_prob=True, **kwargs):
    """One-call MoE layer (dispatch → expert FFN → reduce)."""
    logits = unwrap(x) @ unwrap(gate_weight)
    permuted, token_ids, order, rows, topv = moe_dispatch(
        x, Tensor(logits), moe_topk)
    y = moe_ffn(permuted, rows, up_gate_weight, down_weight)
    return moe_reduce(y, topv, order, token_ids,
                      norm_topk_prob=norm_topk_prob)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    """reference: fused_linear_activation — matmul + bias + activation in
    one XLA fusion."""
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "none": lambda v: v}[activation or "none"]
    return apply(act, out, name="fused_linear_activation")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """reference: fused_multi_transformer — a whole pre-LN decoder stack
    in one call (the CUDA mega-kernel). XLA expresses it as the same
    fused graph; each layer: LN → MHA → residual → LN → FFN → residual."""
    from ...ops.flash_attention import flash_attention as _flash2

    h = x
    L = len(qkv_weights)
    for i in range(L):
        def ln(t, scale, bias_):
            def fn(a, *rest):
                mu = jnp.mean(a, -1, keepdims=True)
                var = jnp.var(a, -1, keepdims=True)
                o = (a - mu) * jax.lax.rsqrt(var + epsilon)
                j = 0
                if scale is not None:
                    o = o * rest[j]; j += 1
                if bias_ is not None:
                    o = o + rest[j]
                return o
            args = [t] + [s for s in (scale, bias_) if s is not None]
            return apply(fn, *args, name="fmt_ln")

        residual = h
        a_in = ln(h, ln_scales[i], ln_biases[i]) if pre_layer_norm else h
        out = fused_multi_head_attention(
            a_in, qkv_weights[i], linear_weights[i],
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_dropout_rate=dropout_rate if training else 0.0,
            training=training)
        h = apply(lambda a, b: a + b, out, residual, name="fmt_res1")
        residual = h
        f_in = ln(h, ffn_ln_scales[i], ffn_ln_biases[i]) \
            if pre_layer_norm else h
        f = fused_matmul_bias(f_in, ffn1_weights[i],
                              ffn1_biases[i] if ffn1_biases else None)
        f = apply({"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation], f,
                  name="fmt_act")
        f = fused_matmul_bias(f, ffn2_weights[i],
                              ffn2_biases[i] if ffn2_biases else None)
        h = apply(lambda a, b: a + b, f, residual, name="fmt_res2")
    return (h, cache_kvs) if cache_kvs is not None else h
