"""memory_efficient_attention (reference:
python/paddle/incubate/nn/memory_efficient_attention.py — the xformers
API over a CUDA kernel).

TPU-native routing — the bias TYPE picks the kernel, so the O(S^2)
bias is only ever materialized when the caller hands us an arbitrary
tensor bias:

  bias type                                   | path
  --------------------------------------------+------------------------
  None                                        | flash kernel
  LowerTriangularMask                         | flash kernel, causal
  BlockDiagonalMask / BlockDiagonalCausalMask | varlen segment kernel
                                              | (one call, no padding)
  Tensor / LowerTriangularMaskWithTensorBias  | XLA attention + bias
  BlockDiagonalCausalWithOffsetPaddedKeysMask | XLA attention with the
                                              | materialized block mask
                                              | (the compiled serving
                                              | engine runs this shape
                                              | on the paged kernel)

query/key/value: (B, S, H, D); GQA (fewer KV heads) is repeated up.
Dropout p follows the reference kernel's semantics (drops attention
probabilities) via the flash/varlen wrappers' dropout path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, unwrap
from ..._core.tensor import apply
from .attn_bias import (
    BlockDiagonalCausalMask,
    BlockDiagonalCausalWithOffsetPaddedKeysMask,
    BlockDiagonalMask,
    LowerTriangularMask,
    LowerTriangularMaskWithTensorBias,
)

__all__ = ["memory_efficient_attention"]

SUPPORTED_ATTN_BIAS_TYPES = {
    type(None),
    Tensor,
    LowerTriangularMask,
    LowerTriangularMaskWithTensorBias,
    BlockDiagonalMask,
    BlockDiagonalCausalMask,
    BlockDiagonalCausalWithOffsetPaddedKeysMask,
}


def _xla_bias_attention(query, key, value, bias, p, scale, training):
    """Generic additive-bias attention: natively differentiable, XLA
    fuses the chain; used only when the mask is an arbitrary tensor."""
    def fn(q, k, v, b):
        qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
        kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
        hq, hk = qh.shape[1], kh.shape[1]
        if hk != hq:
            kh = jnp.repeat(kh, hq // hk, axis=1)
            vh = jnp.repeat(vh, hq // hk, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        bf = jnp.asarray(b, jnp.float32)
        finite = jnp.isfinite(bf)
        # clamp -inf to a large finite negative BEFORE softmax: an -inf
        # row makes the softmax vjp emit NaN that poisons ALL dk/dv even
        # though the forward where() looks clean (same convention as
        # nn/functional scaled_dot_product_attention)
        s = s + jnp.where(finite, bf, -1e30)
        pm = jax.nn.softmax(s, axis=-1)
        # fully-masked query rows output 0, not the uniform average the
        # clamped softmax would give
        pm = jnp.where(finite.any(-1, keepdims=True), pm, 0.0)
        if p > 0.0 and training:
            from ..._core.state import prng
            keep = jax.random.bernoulli(prng.next_key(), 1.0 - p, pm.shape)
            pm = jnp.where(keep, pm / (1.0 - p), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", pm, vh)
        return jnp.swapaxes(o, 1, 2).astype(q.dtype)

    return apply(fn, query, key, value, bias,
                 name="memory_efficient_attention")


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    assert type(attn_bias) in SUPPORTED_ATTN_BIAS_TYPES, \
        f"unsupported attn_bias type {type(attn_bias)}"
    d = query.shape[-1]
    # scale=0 (or negative) is legal and meaningful — only None defaults
    sc = 1.0 / math.sqrt(d) if scale is None else scale

    if isinstance(attn_bias, (BlockDiagonalMask,)):
        # packed varlen: ONE segment-kernel call, no padding, no S^2 mask
        assert query.shape[0] == 1, \
            "block-diagonal biases expect the packed (1, total, H, D) layout"
        from ...ops.varlen_attention import flash_attn_unpadded
        causal = isinstance(attn_bias, BlockDiagonalCausalMask)
        if causal and (attn_bias.q_seqinfo.seqstart_py
                       != attn_bias.k_seqinfo.seqstart_py):
            # per-block causal with UNEQUAL q/k lengths: the varlen
            # kernel's causal is bottom-right aligned, xformers' is
            # top-left — only equal-length blocks agree
            tq, tk = query.shape[1], key.shape[1]
            h = query.shape[2]
            bias = attn_bias.materialize((1, h, tq, tk), dtype="float32")
            return _xla_bias_attention(query, key, value, bias, p, sc,
                                       training)
        cu_q = unwrap(attn_bias.q_seqinfo.seqstart)
        cu_k = unwrap(attn_bias.k_seqinfo.seqstart)

        def fn(q, k, v):
            out, _ = flash_attn_unpadded(
                q[0], k[0], v[0], cu_q, cu_k,
                attn_bias.q_seqinfo.max_seqlen,
                attn_bias.k_seqinfo.max_seqlen,
                scale=sc, dropout=p, causal=causal, training=training)
            return out[None]

        return apply(fn, query, key, value,
                     name="memory_efficient_attention")

    if isinstance(attn_bias, LowerTriangularMaskWithTensorBias):
        b, s_q, h = query.shape[0], query.shape[1], query.shape[2]
        s_k = key.shape[1]
        bias = attn_bias.materialize((b, h, s_q, s_k), dtype="float32")
        return _xla_bias_attention(query, key, value, bias, p, sc, training)

    if isinstance(attn_bias, BlockDiagonalCausalWithOffsetPaddedKeysMask):
        assert query.shape[0] == 1, \
            "padded-keys bias expects the packed (1, total, H, D) layout"
        b, s_q, h = query.shape[0], query.shape[1], query.shape[2]
        s_k = key.shape[1]
        bias = attn_bias.materialize((b, h, s_q, s_k), dtype="float32")
        return _xla_bias_attention(query, key, value, bias, p, sc, training)

    if isinstance(attn_bias, Tensor):
        return _xla_bias_attention(query, key, value, attn_bias, p, sc,
                                   training)

    causal = isinstance(attn_bias, LowerTriangularMask)
    if causal and query.shape[1] != key.shape[1]:
        # xformers' LowerTriangularMask is TOP-LEFT aligned; the flash
        # kernel's causal mode is bottom-right (paddle convention).
        # They agree iff Sq == Sk — rectangular goes via the bias path.
        b, s_q, h = query.shape[0], query.shape[1], query.shape[2]
        bias = attn_bias.materialize((b, h, s_q, key.shape[1]),
                                     dtype="float32")
        return _xla_bias_attention(query, key, value, bias, p, sc, training)

    # None, or square LowerTriangularMask -> dense flash kernel
    from ...ops.flash_attention import flash_attention as _flash

    def fn(q, k, v):
        out, _ = _flash(q, k, v, dropout=p, causal=causal,
                        sm_scale=sc, training=training)
        return out

    return apply(fn, query, key, value, name="memory_efficient_attention")
