"""paddle.incubate.operators (reference: python/paddle/incubate/
operators/): the softmax_mask_fuse pair lives at the incubate top
level (reference re-exports); ResNetUnit here."""
from .resnet_unit import ResNetUnit  # noqa: F401
from .. import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)

__all__ = []
