"""ResNetUnit (reference: python/paddle/incubate/operators/
resnet_unit.py — a cudnnv8 fused conv+BN(+add)+act block).

TPU-native: the unit is the same conv → BN → (+shortcut) → act
composition over our Conv2D/BatchNorm layers; "fused" is XLA's job —
under jit the whole unit compiles into fused convolution/normalization
kernels, which is exactly what the cudnnv8 runtime fusion buys the
reference. Semantics (including has_shortcut vs fuse_add) follow the
reference's forward: out = act(BN(conv(x)) + residual) where residual
is BN(conv(z)) when has_shortcut else z when fuse_add.
"""
from __future__ import annotations

from ...nn.layer.conv import Conv2D
from ...nn.layer.layers import Layer
from ...nn.layer.norm import BatchNorm2D
from ...nn import functional as F

__all__ = ["ResNetUnit"]

_ACTS = {"relu": F.relu, "identity": None, None: None}


class ResNetUnit(Layer):
    def __init__(self, num_channels_x, num_filters, filter_size,
                 stride=1, momentum=0.9, eps=1e-5, data_format="NHWC",
                 act="relu", fuse_add=False, has_shortcut=False,
                 use_global_stats=False, is_test=False,
                 filter_x_attr=None, scale_x_attr=None, bias_x_attr=None,
                 moving_mean_x_name=None, moving_var_x_name=None,
                 num_channels_z=1, stride_z=1, filter_z_attr=None,
                 scale_z_attr=None, bias_z_attr=None,
                 moving_mean_z_name=None, moving_var_z_name=None):
        super().__init__()
        if data_format not in ("NHWC", "NCHW"):
            raise ValueError(
                f"conv_format must be one of {{'NHWC', 'NCHW'}}, but got "
                f"conv_format='{data_format}'")
        if act not in _ACTS:
            raise ValueError(f"ResNetUnit only supports act in "
                             f"{sorted(k for k in _ACTS if k)}, got {act!r}")
        self._fuse_add = fuse_add
        self._has_shortcut = has_shortcut
        self._act = act
        # op-level contract (reference resnet_unit op): the kernel
        # reads use_global_stats alongside is_test — False is the
        # DEFAULT "batch stats in train, moving stats in test" mode,
        # unlike the dygraph BatchNorm layer where an explicit False
        # forces trainable (mini-batch) statistics even in eval. Map
        # the op default to the layer's None before constructing BN.
        use_global_stats = use_global_stats or None
        padding = (filter_size - 1) // 2
        self.conv_x = Conv2D(num_channels_x, num_filters, filter_size,
                             stride=stride, padding=padding,
                             weight_attr=filter_x_attr, bias_attr=False,
                             data_format=data_format)
        self.bn_x = BatchNorm2D(num_filters, momentum=momentum,
                                epsilon=eps, weight_attr=scale_x_attr,
                                bias_attr=bias_x_attr,
                                data_format=data_format,
                                use_global_stats=use_global_stats)
        if has_shortcut:
            self.conv_z = Conv2D(num_channels_z, num_filters, 1,
                                 stride=stride_z, padding=0,
                                 weight_attr=filter_z_attr,
                                 bias_attr=False,
                                 data_format=data_format)
            self.bn_z = BatchNorm2D(num_filters, momentum=momentum,
                                    epsilon=eps, weight_attr=scale_z_attr,
                                    bias_attr=bias_z_attr,
                                    data_format=data_format,
                                    use_global_stats=use_global_stats)
        else:
            self.conv_z = None
            self.bn_z = None
        if is_test:
            # reference is_test=True: inference behavior from
            # construction — moving statistics, no buffer mutation
            self.eval()

    def forward(self, x, z=None):
        out = self.bn_x(self.conv_x(x))
        if self._has_shortcut:
            if z is None:
                raise ValueError("has_shortcut=True requires z")
            out = out + self.bn_z(self.conv_z(z))
        elif self._fuse_add:
            if z is None:
                raise ValueError("fuse_add=True requires z")
            out = out + z
        fn = _ACTS[self._act]
        return fn(out) if fn is not None else out
