"""paddle.incubate optimizer wrappers (reference: python/paddle/incubate/
optimizer: LookAhead, ModelAverage; python/paddle/incubate/
ExponentialMovingAverage).

Pure pytree arithmetic over parameter values — TPU-friendly (each update
is one fused elementwise XLA graph per parameter)."""
from __future__ import annotations

import jax.numpy as jnp

from .._core.tensor import Tensor
from ..optimizer.rules import LarsMomentum as LarsMomentumOptimizer  # noqa: F401
# (reference: python/paddle/incubate/optimizer/__init__.py:18 exports
# LarsMomentumOptimizer from lars_momentum.py)


class ExponentialMovingAverage:
    """EMA of model parameters: shadow ← decay·shadow + (1−decay)·param.

    usage:
        ema = ExponentialMovingAverage(model.parameters(), decay=0.999)
        ... optimizer.step() ...
        ema.update()
        with ema.apply(model):   # eval with averaged weights
            evaluate()
    """

    def __init__(self, parameters, decay=0.999):
        self._params = [p for p in parameters if not p.stop_gradient]
        self._decay = decay
        self._shadow = [jnp.array(p._value) for p in self._params]
        self._backup = None
        self._step = 0

    def update(self):
        self._step += 1
        d = self._decay
        self._shadow = [d * s + (1.0 - d) * p._value
                        for s, p in zip(self._shadow, self._params)]

    def apply_shadow(self):
        self._backup = [jnp.array(p._value) for p in self._params]
        for p, s in zip(self._params, self._shadow):
            p._replace(s.astype(p._value.dtype))

    def restore(self):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._replace(b)
        self._backup = None

    class _Ctx:
        def __init__(self, ema):
            self.ema = ema

        def __enter__(self):
            self.ema.apply_shadow()
            return self.ema

        def __exit__(self, *a):
            self.ema.restore()

    def apply(self, model=None):
        return self._Ctx(self)

    def state_dict(self):
        return {f"shadow_{i}": s for i, s in enumerate(self._shadow)}

    def set_state_dict(self, st):
        self._shadow = [jnp.asarray(st[f"shadow_{i}"])
                        for i in range(len(self._shadow))]


class LookAhead:
    """Lookahead optimizer wrapper (Zhang et al. 2019): every k inner
    steps, slow weights step toward fast weights by alpha and the fast
    weights reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step = 0
        self._slow = None

    def _params(self):
        return [p for p in self.inner_optimizer._parameter_list
                if not p.stop_gradient]

    def step(self):
        if self._slow is None:
            self._slow = [jnp.array(p._value) for p in self._params()]
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k == 0:
            new_slow = []
            for p, s in zip(self._params(), self._slow):
                s = s + self.alpha * (p._value - s)
                p._replace(s.astype(p._value.dtype))
                new_slow.append(s)
            self._slow = new_slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        st = {"inner": self.inner_optimizer.state_dict(),
              "step": self._step}
        if self._slow is not None:
            st["slow"] = {str(i): s for i, s in enumerate(self._slow)}
        return st


class ModelAverage:
    """Running average of parameters over a sliding window (reference:
    incubate ModelAverage with min/max_average_window)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = [p for p in (parameters or [])
                        if not p.stop_gradient]
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = [jnp.zeros_like(p._value) for p in self._params]
        self._count = 0
        self._backup = None

    def step(self):
        self._count += 1
        # exact running mean while count <= window, then sliding EMA:
        # mean_t = mean_{t-1}·(n−1)/n + p/n with n = min(count, window)
        n = min(self._count, self._max_w)
        self._sum = [s * (n - 1.0) / n + p._value / n
                     for s, p in zip(self._sum, self._params)]

    def apply(self, executor=None, need_restore=True):
        self._backup = [jnp.array(p._value) for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._replace(s.astype(p._value.dtype))
        return self

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._replace(b)
        self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.restore()
