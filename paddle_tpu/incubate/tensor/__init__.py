"""paddle.incubate.tensor (reference: python/paddle/incubate/tensor/):
segment reductions + async host-offload manipulation APIs."""
from . import math  # noqa: F401
from . import manipulation  # noqa: F401
from .math import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from .manipulation import (  # noqa: F401
    async_offload, async_offload_with_offset, async_reload,
    create_async_load,
)

__all__ = []
