"""Async host-offload APIs (reference: python/paddle/incubate/tensor/
manipulation.py over core.AsyncLoad — the CUDA pinned-memory D2H/H2D
copy engine used by sharding/offload strategies).

TPU-native: jax dispatch is already asynchronous — `jax.device_put`
returns immediately with a future-backed array and the transfer
overlaps whatever compute is in flight, which is exactly the contract
core.AsyncLoad provides via its background stream. `Task.synchronize`
maps to `block_until_ready`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, unwrap

__all__ = [
    "create_async_load",
    "async_offload",
    "async_reload",
    "async_offload_with_offset",
]


def _host_device():
    """The host-RAM device (cpu backend). On a CPU-only run host and
    device coincide — the API still holds, transfers are no-ops."""
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return jax.devices()[0]


class Task:
    """Handle for an in-flight transfer (reference core AsyncLoad task:
    is_completed / synchronize; cpu_synchronize kept as an alias)."""

    def __init__(self, arrays):
        self._arrays = arrays if isinstance(arrays, (list, tuple)) \
            else [arrays]

    def is_completed(self):
        try:
            return all(a.is_ready() for a in self._arrays)
        except AttributeError:
            return True

    def synchronize(self):
        for a in self._arrays:
            jax.block_until_ready(a)  # tpulint: disable=TPL005 -- Task.synchronize() is an explicit wait

    # reference spells the host-side wait cpu_synchronize
    cpu_synchronize = synchronize
    wait = synchronize


class AsyncLoad:
    """reference core.AsyncLoad. jax's async dispatch is the 'stream';
    the loader additionally remembers each offloaded array's
    accelerator-side placement so reload restores a SHARDED param to
    its original layout instead of gathering everything onto device 0.
    (Tracked per host array via weakref — Tensor has __slots__, so the
    placement can't ride on the wrapper.)"""

    def __init__(self):
        import weakref
        self._placements = weakref.WeakValueDictionary()   # id -> array
        self._shardings = {}                               # id -> sharding

    def offload(self, src):
        raw = unwrap(src)
        dst = jax.device_put(raw, _host_device())
        import weakref
        key = id(dst)
        self._placements[key] = dst
        self._shardings[key] = raw.sharding
        weakref.finalize(dst, self._shardings.pop, key, None)
        return Tensor(dst), Task(dst)

    def reload(self, src):
        raw = unwrap(src)
        key = id(raw)
        # the weak map guards against id reuse: only trust the stored
        # sharding if the SAME array object is still registered
        target = (self._shardings.get(key)
                  if self._placements.get(key) is raw else None)
        dst = jax.device_put(raw, target or jax.devices()[0])
        return Tensor(dst), Task(dst)


def create_async_load():
    """reference manipulation.py:100."""
    return AsyncLoad()


def async_offload(src_tensor, async_load):
    """Device → host-RAM copy, returned immediately as
    (dest_tensor, task); task.synchronize() (or cpu_synchronize) blocks
    until the bytes have landed (reference manipulation.py:105)."""
    return async_load.offload(src_tensor)


def async_reload(src_tensor, async_load):
    """Host-RAM → device copy (reference manipulation.py:121)."""
    return async_load.reload(src_tensor)


def async_offload_with_offset(src_tensor, dst_tensor, src_offset,
                              dst_offset, offload_size, async_loader):
    """Partial 1-D offload: copy `offload_size` elements from
    src[src_offset:] into dst[dst_offset:] (reference
    manipulation.py:139). The scatter into dst is recorded immediately
    (functional update through the Tensor wrapper); the returned task
    gates on the underlying transfer."""
    assert len(src_tensor.shape) == 1, "Only support 1-D tensor"
    assert len(dst_tensor.shape) == 1, "Only support 1-D tensor"
    assert src_tensor.dtype == dst_tensor.dtype, "Only support same dtype"
    # explicit bounds: dynamic_slice/update_slice CLAMP out-of-range
    # starts, which would silently copy/write the wrong elements
    if not (0 <= src_offset and
            src_offset + offload_size <= src_tensor.shape[0]):
        raise ValueError(
            f"src range [{src_offset}, {src_offset + offload_size}) out "
            f"of bounds for length {src_tensor.shape[0]}")
    if not (0 <= dst_offset and
            dst_offset + offload_size <= dst_tensor.shape[0]):
        raise ValueError(
            f"dst range [{dst_offset}, {dst_offset + offload_size}) out "
            f"of bounds for length {dst_tensor.shape[0]}")
    raw_dst = unwrap(dst_tensor)
    try:
        dst_dev = list(raw_dst.devices())[0]
    except Exception:
        dst_dev = _host_device()
    # land the chunk on dst's device first — mixing two COMMITTED
    # placements inside one op is an error in jax
    chunk = jax.device_put(
        jax.lax.dynamic_slice(unwrap(src_tensor), (src_offset,),
                              (offload_size,)),
        dst_dev)
    new_dst = jax.lax.dynamic_update_slice(raw_dst, chunk, (dst_offset,))
    dst_tensor._replace(new_dst)
    return Task(new_dst)
