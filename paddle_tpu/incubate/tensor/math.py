"""Segment reductions (reference: python/paddle/incubate/tensor/math.py
over phi segment_pool kernels). One implementation lives in
paddle_tpu.geometric (jax.ops.segment_* — XLA scatter-reduce on TPU);
these are the incubate-namespace bindings."""
from ...geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max"]
