"""paddle.inference parity: Config + create_predictor
(reference: python/paddle/inference/wrapper.py).

TPU-native: a Predictor wraps a model saved by paddle_tpu.jit.save —
the serialized jax.export (StableHLO) program when present (runs with no
access to the original Python class), else the reconstructed Layer. The
handle-based copy_from_cpu / run / copy_to_cpu flow matches the
reference's zero-copy tensor API; device placement is jax's.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    CUSTOM = "custom"


class Config:
    """reference Config(prog_file, params_file) — here both point at the
    jit.save prefix: Config("dir/model") reads dir/model.pdmodel /
    .pdiparams / .pdexport."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self._threads = 1
        self._memory_optim = True

    def set_prog_file(self, path):
        self.model_prefix = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def prog_file(self):
        return (self.model_prefix or "") + ".pdmodel"

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    # accelerator knobs: jax/XLA owns placement; these are honest no-ops
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=None):
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):
        pass

    def disable_glog_info(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is CUDA-specific; the TPU deployment path is the "
            "exported StableHLO program (already what this Config loads)")


class _IOHandle:
    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(np.asarray(arr))

    def reshape(self, shape):
        pass  # shapes come from the fed array

    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not computed; "
                               f"call predictor.run() first")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    def __init__(self, config: Config):
        from ..jit.api import load
        self._model = load(config.model_prefix)
        n_in = None
        exported = getattr(self._model, "_exported", None)
        if exported is not None:
            n_state = len(self._model._state)
            n_in = len(exported.in_avals) - n_state
        self._n_inputs = n_in if n_in is not None else 1
        self._inputs = {f"x{i}": _IOHandle(f"x{i}")
                        for i in range(self._n_inputs)}
        self._outputs = {}

    def get_input_names(self):
        return list(self._inputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Handle flow (copy_from_cpu beforehand) or direct list-in/
        list-out when `inputs` (list of numpy arrays) is given."""
        if inputs is not None:
            for h, a in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(a)
        args = []
        for name, h in self._inputs.items():
            if h._value is None:
                raise RuntimeError(f"input {name!r} was never fed; call "
                                   f"copy_from_cpu first")
            args.append(h._value)
        out = self._model(*args)
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        self._outputs = {}
        res = []
        for i, leaf in enumerate(leaves):
            handle = _IOHandle(f"out{i}")
            handle._value = unwrap(leaf) if isinstance(leaf, Tensor) else leaf
            self._outputs[f"out{i}"] = handle
            res.append(np.asarray(handle._value))
        return res

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
