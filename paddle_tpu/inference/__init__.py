"""paddle.inference parity: Config + create_predictor
(reference: python/paddle/inference/wrapper.py).

TPU-native: a Predictor wraps a model saved by paddle_tpu.jit.save —
the serialized jax.export (StableHLO) program when present (runs with no
access to the original Python class), else the reconstructed Layer. The
handle-based copy_from_cpu / run / copy_to_cpu flow matches the
reference's zero-copy tensor API; device placement is jax's.
"""
from __future__ import annotations

import os
import shutil

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "DataType", "PredictorPool", "XpuConfig",
           "convert_to_mixed_precision", "get_num_bytes_of_data_type",
           "get_version", "get_trt_compile_version",
           "get_trt_runtime_version",
           # underscore name deliberately public: the reference exports
           # it in paddle.inference.__all__ (inference/__init__.py:46)
           "_get_phi_kernel_name"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class DataType:
    """reference paddle_infer DataType enum (pybind/inference_api.cc)."""
    FLOAT64 = "float64"
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    UINT8 = "uint8"
    INT8 = "int8"
    BOOL = "bool"


def get_num_bytes_of_data_type(dtype) -> int:
    """reference: inference_api.cc GetNumBytesOfDataType."""
    return int(np.dtype(
        jnp.bfloat16 if str(dtype) == "bfloat16" else dtype).itemsize)


def get_version() -> str:
    """Framework version string (reference: paddle_infer::GetVersion)."""
    from .. import version
    return f"paddle_tpu version: {version.full_version}"


def get_trt_compile_version():
    """No TensorRT on TPU (deployment path = StableHLO/XLA AOT)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    """reference: maps fluid op names to PHI kernel names. The XLA
    backend has no PHI registry; the op name is the kernel name."""
    return op_name


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    CUSTOM = "custom"


class Config:
    """reference Config(prog_file, params_file) — here both point at the
    jit.save prefix: Config("dir/model") reads dir/model.pdmodel /
    .pdiparams / .pdexport."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self._threads = 1
        self._memory_optim = True

    def set_prog_file(self, path):
        self.model_prefix = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def prog_file(self):
        return (self.model_prefix or "") + ".pdmodel"

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    # accelerator knobs: jax/XLA owns placement; these are honest no-ops
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=None):
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):
        pass

    def disable_glog_info(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is CUDA-specific; the TPU deployment path is the "
            "exported StableHLO program (already what this Config loads)")


class _IOHandle:
    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(np.asarray(arr))

    def reshape(self, shape):
        pass  # shapes come from the fed array

    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not computed; "
                               f"call predictor.run() first")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    def __init__(self, config: Config):
        from ..jit.api import load
        self._model = load(config.model_prefix)
        n_in = None
        exported = getattr(self._model, "_exported", None)
        if exported is not None:
            n_state = len(self._model._state)
            n_in = len(exported.in_avals) - n_state
        self._n_inputs = n_in if n_in is not None else 1
        self._init_io()

    def _init_io(self):
        self._inputs = {f"x{i}": _IOHandle(f"x{i}")
                        for i in range(self._n_inputs)}
        self._outputs = {}

    @classmethod
    def _share_from(cls, other: "Predictor") -> "Predictor":
        """Pool worker: shares the (immutable) loaded model, owns its IO
        handles. Single construction path — a new Predictor field is
        either copied here or the clone fails loudly, not at retrieve()."""
        self = cls.__new__(cls)
        self.__dict__.update(other.__dict__)
        self._init_io()
        return self

    def get_input_names(self):
        return list(self._inputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Handle flow (copy_from_cpu beforehand) or direct list-in/
        list-out when `inputs` (list of numpy arrays) is given."""
        if inputs is not None:
            for h, a in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(a)
        args = []
        for name, h in self._inputs.items():
            if h._value is None:
                raise RuntimeError(f"input {name!r} was never fed; call "
                                   f"copy_from_cpu first")
            args.append(h._value)
        out = self._model(*args)
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        self._outputs = {}
        res = []
        for i, leaf in enumerate(leaves):
            handle = _IOHandle(f"out{i}")
            handle._value = unwrap(leaf) if isinstance(leaf, Tensor) else leaf
            self._outputs[f"out{i}"] = handle
            res.append(np.asarray(handle._value))
        return res

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """reference paddle_infer::services::PredictorPool — a main
    predictor plus size-1 workers for thread-per-request serving.
    Weights (immutable jax arrays) are shared; every pool member gets
    its own IO handles so concurrent run() calls don't collide."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        first = Predictor(config)
        self._predictors = [first] + [Predictor._share_from(first)
                                      for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._predictors[idx]

    def __len__(self):
        return len(self._predictors)


class XpuConfig:
    """reference XpuConfig (inference_api.cc): vendor-XPU knobs. On TPU
    XLA owns device memory/streams, so these are recorded but inert."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.l3_ptr = None
        self.l3_autotune_size = 0
        self.stream = None
        self.conv_autotune_level = 0
        self.fc_autotune_level = 0


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file,
                               mixed_precision=PrecisionType.Half,
                               backend=PlaceType.CPU, keep_io_types=True,
                               black_list=None, white_list=None):
    """Convert a saved fp32 model to mixed precision (reference:
    python/paddle/inference/wrapper.py:98 over the C++
    convert_to_mixed_precision pass).

    TPU-native shape: the saved artifact is params (pdiparams) + an
    optional jax.export StableHLO program (pdexport). Floating params
    are cast to the target dtype and written to the mixed prefix —
    halving storage/HBM for weights. When the archive reconstructs the
    original Layer class, it then RUNS at the reduced precision; when
    only the exported program is available, the program's baked compute
    dtype is kept and TranslatedLayer casts the stored weights back at
    the boundary (storage-only mixed precision — re-save with
    input_spec under amp to bake reduced-precision compute).

    black_list: parameter-name substrings kept at fp32 (the analogue of
    the reference's per-op blacklist); white_list forces names in.
    Model and params paths are honored independently (the reference
    allows differing basenames, e.g. inference.pdmodel + params.pdiparams).
    """
    import pickle

    def _with(p, suf):
        """Full path for the given artifact: keep an explicit filename;
        strip the OTHER artifact's suffix first so a model_file serving
        as params fallback yields x.pdiparams, not x.pdmodel.pdiparams."""
        if p.endswith(suf):
            return p
        for other in (".pdmodel", ".pdiparams"):
            if p.endswith(other):
                p = p[:-len(other)]
        return p + suf

    if mixed_precision == PrecisionType.Int8:
        raise NotImplementedError(
            "int8 deployment goes through paddle_tpu.quantization PTQ/"
            "QAT, not convert_to_mixed_precision")
    if mixed_precision == PrecisionType.Half:
        target = np.float16
    elif mixed_precision == PrecisionType.Bfloat16:
        import ml_dtypes
        target = ml_dtypes.bfloat16   # a real numpy dtype: host-side cast
    else:
        raise ValueError(
            f"mixed_precision must be PrecisionType.Half or .Bfloat16, "
            f"got {mixed_precision!r} (a silent default would lossily "
            "cast weights)")
    black = set(black_list or ())
    white = set(white_list or ())
    src_model = _with(model_file, ".pdmodel")
    src_params = _with(params_file or model_file, ".pdiparams")
    dst_model = _with(mixed_model_file, ".pdmodel")
    dst_params = _with(mixed_params_file or mixed_model_file, ".pdiparams")
    with open(src_params, "rb") as f:
        state = pickle.load(f)
    with open(src_model, "rb") as f:
        meta = pickle.load(f)

    def keep_fp32(name):
        return any(b in name for b in black) and \
            not any(w in name for w in white)

    cast = {}
    for k, v in state.items():
        arr = np.asarray(v)
        if arr.dtype in (np.float32, np.float64) and not keep_fp32(k):
            # host-side cast: a storage conversion must not round-trip
            # every weight through the accelerator
            arr = arr.astype(target)
        cast[k] = arr
    meta = dict(meta, mixed_precision=str(mixed_precision),
                keep_io_types=bool(keep_io_types))
    for p in (dst_model, dst_params):
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    with open(dst_params, "wb") as f:
        pickle.dump(cast, f)
    with open(dst_model, "wb") as f:
        pickle.dump(meta, f)
    src_export = src_model[:-len(".pdmodel")] + ".pdexport"
    dst_export = dst_model[:-len(".pdmodel")] + ".pdexport"
    if os.path.exists(src_export) and src_export != dst_export:
        shutil.copyfile(src_export, dst_export)
