"""paddle.io (reference: python/paddle/io/*)."""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    SubsetRandomSampler, BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn, default_convert_fn  # noqa: F401
from .dataloader import get_worker_info  # noqa: F401
