"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py +
fluid C++ BlockingQueue workers).

TPU-native pipeline: python worker threads (optionally backed by the
libptio C++ ring buffer for decode/shuffle/batch assembly — see
paddle_tpu/csrc) prefetch host batches; `device_prefetch` double-buffers
jax.device_put so host→HBM copy overlaps step compute.
"""
from __future__ import annotations

import collections
import itertools
import queue
import threading
import time

import numpy as np
import jax

from .._core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_convert_fn(sample):
    if isinstance(sample, Tensor):
        return sample
    if isinstance(sample, np.ndarray):
        return sample
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_convert_fn(s) for s in sample)
    if isinstance(sample, dict):
        return {k: default_convert_fn(v) for k, v in sample.items()}
    return sample


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(col)) for col in transposed)
    return batch


class _ProcessPrefetchIterator:
    """Process-pool prefetch: true parallel Python decode (no GIL).

    Uses the spawn context (fork is unsafe after jax backend init) and the
    jax-free `pt_ioworker` module as the child target — a worker that
    imported paddle_tpu would race the parent for TPU-plugin init and
    deadlock. The dataset/collate_fn must be picklable (and, with a custom
    collate_fn, must not import jax in the child); workers are per-epoch."""

    def __init__(self, loader, index_iter):
        import multiprocessing as mp

        import pt_ioworker
        # forkserver: the server process is a fresh jax-free python (it
        # only imports __main__'s module file, never the parent's loaded
        # jax), and each worker is a cheap fork of it — spawn-level safety
        # at ~ms per-worker startup. Plain fork would clone a live jax/TPU
        # runtime; plain spawn pays a full interpreter+imports per worker.
        try:
            ctx = mp.get_context("forkserver")
        except ValueError:  # pragma: no cover (non-POSIX)
            ctx = mp.get_context("spawn")
        self.loader = loader
        # None → the worker's numpy-only default collate (NOT ours, which
        # would drag paddle_tpu/jax into the child)
        collate = loader.collate_fn
        self.task_q = ctx.Queue()
        self.res_q = ctx.Queue(maxsize=max(
            2, loader.prefetch_factor * loader.num_workers))
        nw = loader.num_workers
        # bounded dispatch: only ~window tasks are outstanding at once, so
        # one slow batch can't make the others pile up in _out_buf (the
        # res_q maxsize alone doesn't bound memory — the in-order server
        # drains it while waiting for the straggler)
        self._tasks = list(index_iter)
        self.n_batches = len(self._tasks)
        self._window = max(2, loader.prefetch_factor * nw) + nw
        self._dispatched = 0
        self.served = 0
        self._sentinels_sent = False
        self._feed_tasks()
        from .._core.state import prng
        base_seed = prng.next_np_seed()  # epoch- and pt.seed()-dependent
        self.procs = []
        for wid in range(nw):
            p = ctx.Process(
                target=pt_ioworker.worker_main,
                args=(self.task_q, self.res_q, loader.dataset, collate,
                      wid, nw, loader.worker_init_fn, base_seed),
                daemon=True)
            p.start()
            self.procs.append(p)
        self._out_buf = {}
        self._next_serve = 0

    def _feed_tasks(self):
        while (self._dispatched < self.n_batches and
               self._dispatched - self.served < self._window):
            self.task_q.put(self._tasks[self._dispatched])
            self._dispatched += 1
        if self._dispatched >= self.n_batches and not self._sentinels_sent:
            for _ in range(self.loader.num_workers):
                self.task_q.put(None)  # one sentinel per worker
            self._sentinels_sent = True

    def __iter__(self):
        return self

    def __next__(self):
        if self.served >= self.n_batches:
            self.shutdown()
            raise StopIteration
        deadline = (time.monotonic() + self.loader.timeout
                    if self.loader.timeout else None)
        while self._next_serve not in self._out_buf:
            try:
                seq, batch = self.res_q.get(timeout=2.0)
            except queue.Empty:
                # blocked-forever guard: if every worker is gone and no
                # result is buffered, the epoch can never finish
                if not any(p.is_alive() for p in self.procs):
                    self.shutdown()
                    raise RuntimeError(
                        "DataLoader worker processes exited before "
                        "producing all batches. If this happened at "
                        "startup, the entry script likely lacks the "
                        "`if __name__ == '__main__':` guard that "
                        "multiprocessing start methods require.")
                if deadline is not None and time.monotonic() > deadline:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self.loader.timeout}s "
                        f"waiting for worker batches")
                continue
            self._out_buf[seq] = batch
        batch = self._out_buf.pop(self._next_serve)
        self._next_serve += 1
        self.served += 1
        self._feed_tasks()
        if isinstance(batch, Exception):
            self.shutdown()
            raise batch
        return _to_tensors(batch, self.loader.return_list)

    def shutdown(self):
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        for p in self.procs:
            p.join(timeout=5)

    def __del__(self):  # pragma: no cover
        try:
            self.shutdown()
        except Exception:
            pass


class _PrefetchIterator:
    """Threaded prefetch with bounded queue (C++ libptio ring used for the
    byte-level pipeline when enabled)."""

    def __init__(self, loader, index_iter):
        self.loader = loader
        self.dataset = loader.dataset
        self.collate = loader.collate_fn or default_collate_fn
        self.out_q = queue.Queue(maxsize=max(2, loader.prefetch_factor *
                                             max(loader.num_workers, 1)))
        self.idx_q = queue.Queue()
        self.n_batches = 0
        for b in index_iter:
            self.idx_q.put(b)
            self.n_batches += 1
        self.served = 0
        self.workers = []
        self._stop = threading.Event()
        nw = max(loader.num_workers, 1)
        for wid in range(nw):
            t = threading.Thread(target=self._work, args=(wid, nw), daemon=True)
            t.start()
            self.workers.append(t)
        self._out_buf = {}
        self._next_serve = 0
        self._order = collections.deque(range(self.n_batches))

    def _work(self, wid, nw):
        _worker_info.info = WorkerInfo(wid, nw, self.dataset)
        if self.loader.worker_init_fn:
            self.loader.worker_init_fn(wid)
        while not self._stop.is_set():
            try:
                item = self.idx_q.get_nowait()
            except queue.Empty:
                return
            seq, indices = item
            try:
                samples = [self.dataset[i] for i in indices]
                batch = self.collate(samples)
            except Exception as e:  # surface worker errors to the consumer
                batch = e
            self.out_q.put((seq, batch))

    def __iter__(self):
        return self

    def __next__(self):
        if self.served >= self.n_batches:
            raise StopIteration
        while self._next_serve not in self._out_buf:
            seq, batch = self.out_q.get()
            self._out_buf[seq] = batch
        batch = self._out_buf.pop(self._next_serve)
        self._next_serve += 1
        self.served += 1
        if isinstance(batch, Exception):
            raise batch
        return _to_tensors(batch, self.loader.return_list)

    def shutdown(self):
        self._stop.set()


def _to_tensors(batch, return_list=True):
    import jax.numpy as jnp

    def conv(x):
        if isinstance(x, np.ndarray):
            return Tensor(jnp.asarray(x))
        if isinstance(x, Tensor):
            return x
        return x
    if isinstance(batch, dict):
        return {k: conv(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [conv(v) if not isinstance(v, (list, tuple, dict)) else
                _to_tensors(v, return_list) for v in batch]
    return conv(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 use_process_workers=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn
        if num_workers == 0:
            # incubate.autotune dataloader section (reference: fluid's
            # dataloader auto-tuning measures and adjusts num_workers;
            # here the enabled flag upgrades an untuned default)
            try:
                from ..incubate.autotune import get_config
                dl = get_config()["dataloader"]
                if dl.get("enable"):
                    import os as _os
                    num_workers = int(dl.get(
                        "num_workers",
                        min(4, max(1, (_os.cpu_count() or 2) // 2))))
            except Exception:
                pass
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        # process workers decode Python datasets in true parallel (paddle's
        # _DataLoaderIterMultiProcess); threads remain the default because
        # they need no picklability and libptio covers the byte pipeline
        if use_process_workers is None:
            import os
            use_process_workers = os.environ.get(
                "PT_DATALOADER_PROCS", "0") == "1"
        self.use_process_workers = use_process_workers
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        self._drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:
            # sample mode: yield converted single samples
            return (_to_tensors(default_convert_fn(self.dataset[i]))
                    for i in range(len(self.dataset)))
        if self.num_workers == 0:
            return self._iter_sync()
        if self.use_process_workers:
            return _ProcessPrefetchIterator(
                self, enumerate(iter(self.batch_sampler)))
        it = _PrefetchIterator(self, enumerate(iter(self.batch_sampler)))
        return it

    def _iter_sync(self):
        collate = self.collate_fn or default_collate_fn
        for indices in self.batch_sampler:
            samples = [self.dataset[i] for i in indices]
            yield _to_tensors(collate(samples), self.return_list)

    def _iter_iterable(self):
        collate = self.collate_fn or default_collate_fn
        buf = []
        for sample in self.dataset:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield _to_tensors(collate(buf), self.return_list)
                buf = []
        if buf and not getattr(self, "drop_last", False):
            yield _to_tensors(collate(buf), self.return_list)


def device_prefetch(iterator, device=None, depth=2):
    """Double-buffered host→device pipeline: keeps `depth` batches in
    flight via jax async dispatch so H2D overlaps compute."""
    import jax.numpy as jnp

    def put(batch):
        return jax.tree_util.tree_map(
            lambda t: jax.device_put(t._value if isinstance(t, Tensor) else t,
                                     device),
            batch, is_leaf=lambda t: isinstance(t, Tensor))
    buf = collections.deque()
    it = iter(iterator)
    try:
        for _ in range(depth):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
