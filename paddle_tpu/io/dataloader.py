"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py +
fluid C++ BlockingQueue workers).

TPU-native pipeline: python worker threads (optionally backed by the
libptio C++ ring buffer for decode/shuffle/batch assembly — see
paddle_tpu/csrc) prefetch host batches; `device_prefetch` double-buffers
jax.device_put so host→HBM copy overlaps step compute.
"""
from __future__ import annotations

import collections
import itertools
import queue
import threading

import numpy as np
import jax

from .._core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_convert_fn(sample):
    if isinstance(sample, Tensor):
        return sample
    if isinstance(sample, np.ndarray):
        return sample
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_convert_fn(s) for s in sample)
    if isinstance(sample, dict):
        return {k: default_convert_fn(v) for k, v in sample.items()}
    return sample


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(col)) for col in transposed)
    return batch


class _PrefetchIterator:
    """Threaded prefetch with bounded queue (C++ libptio ring used for the
    byte-level pipeline when enabled)."""

    def __init__(self, loader, index_iter):
        self.loader = loader
        self.dataset = loader.dataset
        self.collate = loader.collate_fn or default_collate_fn
        self.out_q = queue.Queue(maxsize=max(2, loader.prefetch_factor *
                                             max(loader.num_workers, 1)))
        self.idx_q = queue.Queue()
        self.n_batches = 0
        for b in index_iter:
            self.idx_q.put(b)
            self.n_batches += 1
        self.served = 0
        self.workers = []
        self._stop = threading.Event()
        nw = max(loader.num_workers, 1)
        for wid in range(nw):
            t = threading.Thread(target=self._work, args=(wid, nw), daemon=True)
            t.start()
            self.workers.append(t)
        self._out_buf = {}
        self._next_serve = 0
        self._order = collections.deque(range(self.n_batches))

    def _work(self, wid, nw):
        _worker_info.info = WorkerInfo(wid, nw, self.dataset)
        if self.loader.worker_init_fn:
            self.loader.worker_init_fn(wid)
        while not self._stop.is_set():
            try:
                item = self.idx_q.get_nowait()
            except queue.Empty:
                return
            seq, indices = item
            try:
                samples = [self.dataset[i] for i in indices]
                batch = self.collate(samples)
            except Exception as e:  # surface worker errors to the consumer
                batch = e
            self.out_q.put((seq, batch))

    def __iter__(self):
        return self

    def __next__(self):
        if self.served >= self.n_batches:
            raise StopIteration
        while self._next_serve not in self._out_buf:
            seq, batch = self.out_q.get()
            self._out_buf[seq] = batch
        batch = self._out_buf.pop(self._next_serve)
        self._next_serve += 1
        self.served += 1
        if isinstance(batch, Exception):
            raise batch
        return _to_tensors(batch, self.loader.return_list)

    def shutdown(self):
        self._stop.set()


def _to_tensors(batch, return_list=True):
    import jax.numpy as jnp

    def conv(x):
        if isinstance(x, np.ndarray):
            return Tensor(jnp.asarray(x))
        if isinstance(x, Tensor):
            return x
        return x
    if isinstance(batch, dict):
        return {k: conv(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [conv(v) if not isinstance(v, (list, tuple, dict)) else
                _to_tensors(v, return_list) for v in batch]
    return conv(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        self._drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:
            # sample mode: yield converted single samples
            return (_to_tensors(default_convert_fn(self.dataset[i]))
                    for i in range(len(self.dataset)))
        if self.num_workers == 0:
            return self._iter_sync()
        it = _PrefetchIterator(self, enumerate(iter(self.batch_sampler)))
        return it

    def _iter_sync(self):
        collate = self.collate_fn or default_collate_fn
        for indices in self.batch_sampler:
            samples = [self.dataset[i] for i in indices]
            yield _to_tensors(collate(samples), self.return_list)

    def _iter_iterable(self):
        collate = self.collate_fn or default_collate_fn
        buf = []
        for sample in self.dataset:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield _to_tensors(collate(buf), self.return_list)
                buf = []
        if buf and not getattr(self, "drop_last", False):
            yield _to_tensors(collate(buf), self.return_list)


def device_prefetch(iterator, device=None, depth=2):
    """Double-buffered host→device pipeline: keeps `depth` batches in
    flight via jax async dispatch so H2D overlaps compute."""
    import jax.numpy as jnp

    def put(batch):
        return jax.tree_util.tree_map(
            lambda t: jax.device_put(t._value if isinstance(t, Tensor) else t,
                                     device),
            batch, is_leaf=lambda t: isinstance(t, Tensor))
    buf = collections.deque()
    it = iter(iterator)
    try:
        for _ in range(depth):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
