"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from .._core.state import prng
    import jax
    total = len(dataset)
    lens = list(lengths)
    if all(isinstance(l, float) for l in lens) and abs(sum(lens) - 1.0) < 1e-6:
        lens = [int(np.floor(total * l)) for l in lens]
        rem = total - sum(lens)
        for i in range(rem):
            lens[i % len(lens)] += 1
    if sum(lens) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.asarray(jax.random.permutation(prng.next_key(), total))
    out = []
    offset = 0
    for l in lens:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out
