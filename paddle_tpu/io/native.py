"""ctypes bindings for libptio (C++ data-pipeline core) + RecordFile
dataset/loader.

The native path covers the byte-level hot loop (mmap read, shuffle,
batch memcpy, prefetch) that the reference does in
paddle/fluid/operators/reader; Python only sees finished batches.
Builds lazily on first use (`make -C paddle_tpu/csrc`).
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = os.path.join(_CSRC, "libptio.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", _CSRC], check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    lib.ptio_open_records.restype = ctypes.c_void_p
    lib.ptio_open_records.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptio_num_records.restype = ctypes.c_int64
    lib.ptio_num_records.argtypes = [ctypes.c_void_p]
    lib.ptio_close_records.argtypes = [ctypes.c_void_p]
    lib.ptio_pipeline_create.restype = ctypes.c_void_p
    lib.ptio_pipeline_create.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_int, ctypes.c_int,
                                         ctypes.c_uint64, ctypes.c_int64]
    lib.ptio_pipeline_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                              ctypes.c_int]
    lib.ptio_pipeline_num_batches.restype = ctypes.c_int64
    lib.ptio_pipeline_num_batches.argtypes = [ctypes.c_void_p]
    lib.ptio_pipeline_next.restype = ctypes.c_int64
    lib.ptio_pipeline_next.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint8)]
    lib.ptio_pipeline_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def available():
    try:
        _load()
        return True
    except Exception:
        return False


def write_record_file(path, array):
    """Serialize a (N, ...) array as fixed-size raw records."""
    arr = np.ascontiguousarray(array)
    arr.tofile(path)
    return arr.shape, arr.dtype


class RecordFileDataset:
    """Fixed-record binary dataset backed by mmap (native)."""

    def __init__(self, path, record_shape, dtype):
        self.record_shape = tuple(record_shape)
        self.dtype = np.dtype(dtype)
        self.record_bytes = int(np.prod(self.record_shape)) * self.dtype.itemsize
        lib = _load()
        self._h = lib.ptio_open_records(str(path).encode(), self.record_bytes)
        if not self._h:
            raise IOError(f"cannot open record file {path}")
        self._n = lib.ptio_num_records(self._h)

    def __len__(self):
        return self._n

    def close(self):
        if self._h:
            _load().ptio_close_records(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeDataLoader:
    """Multithreaded prefetching loader over a RecordFileDataset.

    Yields np arrays (batch, *record_shape); shuffle reshuffles per epoch
    in C++ (deterministic from seed+epoch).
    """

    def __init__(self, dataset: RecordFileDataset, batch_size=1, shuffle=False,
                 drop_last=True, seed=0, num_threads=2, capacity=8):
        self.ds = dataset
        self.batch_size = batch_size
        self.num_threads = num_threads
        lib = _load()
        self._p = lib.ptio_pipeline_create(dataset._h, batch_size,
                                           1 if shuffle else 0,
                                           1 if drop_last else 0, seed, capacity)
        self._epoch = 0
        self._buf = np.empty((batch_size,) + dataset.record_shape,
                             dtype=dataset.dtype)

    def __len__(self):
        # pure count (never touches epoch state — calling len() mid-
        # iteration must not restart the pipeline)
        return _load().ptio_pipeline_num_batches(self._p)

    def __iter__(self):
        lib = _load()
        lib.ptio_pipeline_start_epoch(self._p, self._epoch, self.num_threads)
        self._epoch += 1
        ptr = self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            n = lib.ptio_pipeline_next(self._p, ptr)
            if n <= 0:
                break
            yield np.array(self._buf[:n], copy=True)

    def __del__(self):
        try:
            if self._p:
                _load().ptio_pipeline_destroy(self._p)
                self._p = None
        except Exception:
            pass


# ---------------------------------------------------------------- varlen
def _load_varlen():
    lib = _load()
    if getattr(lib, "_varlen_bound", False):
        return lib
    lib.ptio_open_varlen.restype = ctypes.c_void_p
    lib.ptio_open_varlen.argtypes = [ctypes.c_char_p]
    lib.ptio_varlen_num_records.restype = ctypes.c_int64
    lib.ptio_varlen_num_records.argtypes = [ctypes.c_void_p]
    lib.ptio_varlen_max_record.restype = ctypes.c_int64
    lib.ptio_varlen_max_record.argtypes = [ctypes.c_void_p]
    lib.ptio_close_varlen.argtypes = [ctypes.c_void_p]
    lib.ptio_varlen_pipeline_create.restype = ctypes.c_void_p
    lib.ptio_varlen_pipeline_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int64]
    lib.ptio_varlen_pipeline_start_epoch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.ptio_varlen_pipeline_num_batches.restype = ctypes.c_int64
    lib.ptio_varlen_pipeline_num_batches.argtypes = [ctypes.c_void_p]
    lib.ptio_varlen_pipeline_next.restype = ctypes.c_int64
    lib.ptio_varlen_pipeline_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64)]
    lib.ptio_varlen_pipeline_destroy.argtypes = [ctypes.c_void_p]
    lib._varlen_bound = True
    return lib


def write_varlen_records(path, records):
    """Pack an iterable of bytes-like records into a .ptvr file
    ("PTVR" + u32 version + u64 n + u64 offsets[n+1] + blob)."""
    import struct
    blobs = [bytes(memoryview(np.ascontiguousarray(r)).cast("B"))
             if isinstance(r, np.ndarray) else bytes(r) for r in records]
    offs = [0]
    for b in blobs:
        offs.append(offs[-1] + len(b))
    with open(path, "wb") as f:
        f.write(b"PTVR")
        f.write(struct.pack("<I", 1))
        f.write(struct.pack("<Q", len(blobs)))
        f.write(np.asarray(offs, np.uint64).tobytes())
        for b in blobs:
            f.write(b)
    return len(blobs)


class VarlenRecordDataset:
    """Variable-length binary record dataset (native mmap; validated
    index — the serving/LLM token-sequence layout the fixed-record path
    can't express)."""

    def __init__(self, path):
        lib = _load_varlen()
        self._h = lib.ptio_open_varlen(str(path).encode())
        if not self._h:
            raise IOError(f"cannot open varlen record file {path} "
                          f"(missing, truncated, or corrupt index)")
        self._n = lib.ptio_varlen_num_records(self._h)
        self.max_record = lib.ptio_varlen_max_record(self._h)

    def __len__(self):
        return self._n

    def close(self):
        if self._h:
            _load_varlen().ptio_close_varlen(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeVarlenLoader:
    """Prefetching loader over variable-length records.

    Yields lists of uint8 arrays (one per record, exact sizes); pass
    `decode` (e.g. lambda b: np.frombuffer(b, np.int32)) to map bytes
    to samples in the worker-free consumer loop.
    """

    def __init__(self, dataset: VarlenRecordDataset, batch_size=1,
                 shuffle=False, drop_last=True, seed=0, num_threads=2,
                 capacity=8, decode=None):
        self.ds = dataset
        self.batch_size = batch_size
        self.num_threads = num_threads
        self.decode = decode
        lib = _load_varlen()
        self._p = lib.ptio_varlen_pipeline_create(
            dataset._h, batch_size, 1 if shuffle else 0,
            1 if drop_last else 0, seed, capacity)
        self._epoch = 0
        self._buf = np.empty(batch_size * max(int(dataset.max_record), 1),
                             np.uint8)
        self._sizes = np.empty(batch_size, np.int64)

    def __len__(self):
        # pure count (never touches epoch state)
        return _load_varlen().ptio_varlen_pipeline_num_batches(self._p)

    def __iter__(self):
        lib = _load_varlen()
        lib.ptio_varlen_pipeline_start_epoch(self._p, self._epoch,
                                             self.num_threads)
        self._epoch += 1
        bptr = self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        sptr = self._sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        while True:
            n = lib.ptio_varlen_pipeline_next(self._p, bptr, sptr)
            if n <= 0:
                break
            out, off = [], 0
            for i in range(n):
                sz = int(self._sizes[i])
                rec = np.array(self._buf[off:off + sz], copy=True)
                off += sz
                out.append(self.decode(rec) if self.decode else rec)
            yield out

    def __del__(self):
        try:
            if self._p:
                _load_varlen().ptio_varlen_pipeline_destroy(self._p)
                self._p = None
        except Exception:
            pass
