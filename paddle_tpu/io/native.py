"""ctypes bindings for libptio (C++ data-pipeline core) + RecordFile
dataset/loader.

The native path covers the byte-level hot loop (mmap read, shuffle,
batch memcpy, prefetch) that the reference does in
paddle/fluid/operators/reader; Python only sees finished batches.
Builds lazily on first use (`make -C paddle_tpu/csrc`).
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = os.path.join(_CSRC, "libptio.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", _CSRC], check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    lib.ptio_open_records.restype = ctypes.c_void_p
    lib.ptio_open_records.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptio_num_records.restype = ctypes.c_int64
    lib.ptio_num_records.argtypes = [ctypes.c_void_p]
    lib.ptio_close_records.argtypes = [ctypes.c_void_p]
    lib.ptio_pipeline_create.restype = ctypes.c_void_p
    lib.ptio_pipeline_create.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_int, ctypes.c_int,
                                         ctypes.c_uint64, ctypes.c_int64]
    lib.ptio_pipeline_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                              ctypes.c_int]
    lib.ptio_pipeline_num_batches.restype = ctypes.c_int64
    lib.ptio_pipeline_num_batches.argtypes = [ctypes.c_void_p]
    lib.ptio_pipeline_next.restype = ctypes.c_int64
    lib.ptio_pipeline_next.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint8)]
    lib.ptio_pipeline_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def available():
    try:
        _load()
        return True
    except Exception:
        return False


def write_record_file(path, array):
    """Serialize a (N, ...) array as fixed-size raw records."""
    arr = np.ascontiguousarray(array)
    arr.tofile(path)
    return arr.shape, arr.dtype


class RecordFileDataset:
    """Fixed-record binary dataset backed by mmap (native)."""

    def __init__(self, path, record_shape, dtype):
        self.record_shape = tuple(record_shape)
        self.dtype = np.dtype(dtype)
        self.record_bytes = int(np.prod(self.record_shape)) * self.dtype.itemsize
        lib = _load()
        self._h = lib.ptio_open_records(str(path).encode(), self.record_bytes)
        if not self._h:
            raise IOError(f"cannot open record file {path}")
        self._n = lib.ptio_num_records(self._h)

    def __len__(self):
        return self._n

    def close(self):
        if self._h:
            _load().ptio_close_records(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeDataLoader:
    """Multithreaded prefetching loader over a RecordFileDataset.

    Yields np arrays (batch, *record_shape); shuffle reshuffles per epoch
    in C++ (deterministic from seed+epoch).
    """

    def __init__(self, dataset: RecordFileDataset, batch_size=1, shuffle=False,
                 drop_last=True, seed=0, num_threads=2, capacity=8):
        self.ds = dataset
        self.batch_size = batch_size
        self.num_threads = num_threads
        lib = _load()
        self._p = lib.ptio_pipeline_create(dataset._h, batch_size,
                                           1 if shuffle else 0,
                                           1 if drop_last else 0, seed, capacity)
        self._epoch = 0
        self._buf = np.empty((batch_size,) + dataset.record_shape,
                             dtype=dataset.dtype)

    def __len__(self):
        lib = _load()
        lib.ptio_pipeline_start_epoch(self._p, self._epoch, 0)
        return lib.ptio_pipeline_num_batches(self._p)

    def __iter__(self):
        lib = _load()
        lib.ptio_pipeline_start_epoch(self._p, self._epoch, self.num_threads)
        self._epoch += 1
        ptr = self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            n = lib.ptio_pipeline_next(self._p, ptr)
            if n <= 0:
                break
            yield np.array(self._buf[:n], copy=True)

    def __del__(self):
        try:
            if self._p:
                _load().ptio_pipeline_destroy(self._p)
                self._p = None
        except Exception:
            pass
