"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py)."""
from __future__ import annotations

import numpy as np

from .._core import state as _state


def _new_rng(generator=None) -> np.random.Generator:
    """Per-iteration Generator: deterministic under paddle_tpu.seed() and
    immune to cross-thread contention on numpy's legacy global RNG.

    Accepts np.random.Generator / RandomState / int seeds; any other object
    (e.g. a paddle-API Generator handle) falls back to the framework seed
    stream rather than crashing."""
    if isinstance(generator, np.random.Generator):
        return generator
    if isinstance(generator, np.random.RandomState):
        return np.random.default_rng(generator.randint(0, 2**32))
    if isinstance(generator, (int, np.integer)):
        return np.random.default_rng(int(generator))
    return np.random.default_rng(_state.prng.next_np_seed())


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None \
            else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = _new_rng(self.generator)
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = _new_rng().choice(len(self.weights), self.num_samples,
                                replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        yield from _new_rng().permutation(self.indices).tolist()

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py). On TPU the "rank" is
    the process index for multi-host, or a dp shard for per-host mesh."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        from ..distributed import env as _env
        self.nranks = num_replicas if num_replicas is not None else \
            _env.get_world_size()
        self.local_rank = rank if rank is not None else _env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks)) \
            if not drop_last else len(dataset) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible
        if not self.drop_last:
            indices += indices[: self.total_size - len(indices)]
        else:
            indices = indices[: self.total_size]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
