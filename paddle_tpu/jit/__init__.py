"""paddle.jit parity (reference: python/paddle/jit/*).

dy2static (SOT/AST → PIR → CINN) collapses to trace+XLA-compile on TPU:
`to_static(fn)` jit-compiles the functional form of fn/Layer. save/load
serialize params + a re-traceable spec.
"""
from .api import to_static, not_to_static, save, load, ignore_module  # noqa: F401
from .api import enable_to_static, TranslatedLayer, InputSpec  # noqa: F401
from .api import set_code_level, set_verbosity  # noqa: F401
