"""jit.api (reference: python/paddle/jit/api.py).

The execution model IS trace-once/compile on TPU, so to_static is a thin
adapter: Layer forward → functional_call → jax.jit with donated params.
"""
from __future__ import annotations

import functools
import os
import pickle
import time

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, Parameter, unwrap
from ..nn.layer.layers import Layer

_to_static_enabled = True


def enable_to_static(flag=True):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def disable_static():
    pass  # dynamic mode is the only mode; parity shim


def enable_static():
    pass  # static graph API served via paddle_tpu.static facade


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = shape
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


class StaticFunction:
    """Compiled callable wrapping a Layer method or plain function."""

    def __init__(self, function, input_spec=None, layer=None, **kwargs):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = {}
        functools.update_wrapper(self, function)

    def _key(self, args):
        def sig(a):
            if isinstance(a, Tensor):
                return ("T", tuple(a.shape), str(a.dtype))
            if isinstance(a, (jnp.ndarray, np.ndarray)):
                return ("A", tuple(a.shape), str(a.dtype))
            return ("S", a if isinstance(a, (int, float, str, bool, type(None)))
                    else str(type(a)))
        return tuple(sig(a) for a in args)

    def _note_call(self, key, elapsed_s, jitted=None, call_args=()):
        """Compile telemetry: the shape key IS jit's cache key, so a
        first-seen key is a compile (counted, timed, retrace-warned).
        A compile also captures the executable's XLA cost/memory
        analysis, and every call feeds the MFU window."""
        from ..observability import device_telemetry as _dt
        from ..observability.compile_telemetry import REGISTRY
        name = getattr(self._function, "__qualname__",
                       self._function.__name__)
        label = f"to_static:{name}"
        compiled = REGISTRY.note_call(label, key, elapsed_s)
        if compiled and jitted is not None:
            _dt.COSTS.capture(label, key, jitted, call_args)
        _dt.COSTS.note_executed(label, key)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._function(*args, **kwargs)
        layer = self._layer
        if layer is None and args and isinstance(args[0], Layer):
            layer = args[0]
            args = args[1:]
        if layer is None:
            # plain function: jit over raw arrays
            key = self._key(args)
            if key not in self._jitted:
                fn = self._function

                def pure(*raws):
                    wrapped = [Tensor(r) if isinstance(r, jax.Array) else r
                               for r in raws]
                    out = fn(*wrapped, **kwargs)
                    return jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda t: isinstance(t, Tensor))
                self._jitted[key] = jax.jit(pure)
            raws = tuple(unwrap(a) if isinstance(a, Tensor) else a for a in args)
            t0 = time.perf_counter()
            out = self._jitted[key](*raws)
            self._note_call(key, time.perf_counter() - t0,
                            jitted=self._jitted[key], call_args=raws)
            return jax.tree_util.tree_map(Tensor, out)
        # Layer method: functional over (params, buffers, inputs)
        key = self._key(args)
        if key not in self._jitted:
            fn = self._function

            def pure(params, buffers, *raws):
                wrapped = [Tensor(r) if isinstance(r, jax.Array) else r
                           for r in raws]
                with layer._swapped_state(params, buffers):
                    out = fn(layer, *wrapped, **kwargs) if _is_method(fn) else \
                        fn(*wrapped, **kwargs)
                return jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            self._jitted[key] = jax.jit(pure)
        params, buffers = layer.functional_state()
        raws = tuple(unwrap(a) if isinstance(a, Tensor) else a for a in args)
        t0 = time.perf_counter()
        out = self._jitted[key](params, buffers, *raws)
        self._note_call(key, time.perf_counter() - t0,
                        jitted=self._jitted[key],
                        call_args=(params, buffers) + raws)
        return jax.tree_util.tree_map(Tensor, out)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._function)


def _is_method(fn):
    import inspect
    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    def decorate(fn_or_layer):
        if isinstance(fn_or_layer, Layer):
            layer = fn_or_layer
            layer.forward = StaticFunction(layer.forward.__func__
                                           if hasattr(layer.forward, "__func__")
                                           else layer.forward,
                                           input_spec, layer=layer)
            return layer
        return StaticFunction(fn_or_layer, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer(Layer):
    """Loaded inference layer (reference: translated_layer.py).

    When the saved model carries a jax.export program (.pdexport), forward
    executes that serialized StableHLO directly — no access to the
    original Python class is needed, matching the reference's
    load-and-run contract."""

    def __init__(self, state, exported=None):
        super().__init__()
        self._state = state
        self._exported = exported
        self._call_params = None   # aval-dtype-matched, built once

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "TranslatedLayer: this archive has no exported program "
                "(saved without input_spec); re-save with input_spec or "
                "reconstruct the original class to run")
        if self._call_params is None:
            params = [unwrap(self._state[k]) for k in sorted(self._state)]
            # params stored at a different precision than the exported
            # program's avals (inference.convert_to_mixed_precision
            # writes half/bf16 storage next to the unchanged program):
            # cast back ONCE — the export's compute dtype is baked in,
            # and re-casting per call would churn a full weight copy
            # per request
            avals = self._exported.in_avals[:len(params)]
            self._call_params = [
                p if p.dtype == a.dtype else jnp.asarray(p, a.dtype)
                for p, a in zip(params, avals)]
        raws = [unwrap(a) if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out = self._exported.call(*self._call_params, *raws)
        return jax.tree_util.tree_map(Tensor, out)


def _spec_to_struct(spec, scope, counter, example=None):
    """InputSpec → ShapeDtypeStruct; None/-1 dims become jax.export
    symbolic dimensions (shared scope), so the exported program runs at
    ANY batch size instead of silently baking in 1."""
    from jax import export as jexport

    from .._core import dtypes as _dt
    if example is not None:
        v = unwrap(example)
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
    parts = []
    for s in spec.shape:
        if s in (None, -1):
            counter[0] += 1
            parts.append(f"_d{counter[0]}")
        else:
            parts.append(str(int(s)))
    if any(p.startswith("_d") for p in parts):
        shape = jexport.symbolic_shape(", ".join(parts), scope=scope)
    else:
        shape = tuple(int(p) for p in parts)
    return jax.ShapeDtypeStruct(shape, _dt.convert_dtype(spec.dtype))


def save(layer, path, input_spec=None, **configs):
    """Serialize params + class info + (with input_spec or example
    inputs) the traced computation via jax.export — the XLA-AOT
    deployment path (reference: jit.save → Program + pdiparams)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        raise TypeError("save a Layer, not a StaticFunction")
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__name__, "module": type(layer).__module__}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)
    if input_spec:
        from jax import export as jexport
        params, buffers = layer.functional_state()
        state_keys = sorted(layer.state_dict().keys())

        def pure(*flat):
            n = len(state_keys)
            sd = dict(zip(state_keys, flat[:n]))
            p = {k: sd[k] for k in params if k in sd}
            bu = {k: sd.get(k, v) for k, v in buffers.items()}
            inputs = [Tensor(r) for r in flat[n:]]
            with layer._swapped_state({**params, **p}, bu):
                out = layer(*inputs)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        sd_now = layer.state_dict()
        param_structs = [jax.ShapeDtypeStruct(tuple(sd_now[k].shape),
                                              unwrap(sd_now[k]).dtype)
                         for k in state_keys]
        scope = jexport.SymbolicScope()
        counter = [0]
        in_structs = [s if isinstance(s, jax.ShapeDtypeStruct)
                      else _spec_to_struct(s, scope, counter)
                      for s in input_spec]
        was_training = layer.training
        layer.eval()
        try:
            exp = jexport.export(jax.jit(pure))(*param_structs, *in_structs)
        finally:
            if was_training:
                layer.train()
        with open(path + ".pdexport", "wb") as f:
            f.write(exp.serialize())


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    import importlib
    try:
        mod = importlib.import_module(meta["module"])
        cls = getattr(mod, meta["class"])
        try:
            layer = cls()
            # only trust the reconstruction when its parameter tree matches
            # the archive — a default-constructed container (Sequential())
            # would otherwise pass as an empty identity model
            if set(layer.state_dict().keys()) == set(state.keys()):
                if meta.get("mixed_precision"):
                    # a convert_to_mixed_precision archive must RUN at
                    # the STORED per-key precision: black_listed params
                    # stay fp32 while the rest are half/bf16, so
                    # neither set_state_dict (casts to the fresh
                    # layer's fp32) nor a uniform .to(mixed) (casts
                    # the protected fp32 params down) is right —
                    # adopt each stored array's dtype directly
                    own = layer.state_dict()
                    for k, v in state.items():
                        own[k]._replace(jnp.asarray(v))
                else:
                    layer.set_state_dict({k: Tensor(jnp.asarray(v))
                                          for k, v in state.items()})
                return layer
        except TypeError:
            pass
    except Exception:
        pass
    state_t = {k: Tensor(jnp.asarray(v)) for k, v in state.items()}
    exported = None
    if os.path.exists(path + ".pdexport"):
        from jax import export as jexport
        with open(path + ".pdexport", "rb") as f:
            exported = jexport.deserialize(f.read())
    return TranslatedLayer(state_t, exported)


_verbosity = 0


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit/dy2static logging verbosity. Trace-compile on TPU
    has no transpiler stages; this toggles jax compilation logging."""
    global _verbosity
    _verbosity = int(level)
    import logging
    logging.getLogger("jax").setLevel(
        logging.DEBUG if level >= 3 else
        logging.INFO if level >= 1 else logging.WARNING)


def set_code_level(level=100, also_to_stdout=False):
    """reference: prints transformed code at each dy2static stage. There
    is no AST transpiler here (trace-once jit); kept as a logging shim."""
    set_verbosity(1 if level else 0, also_to_stdout)
