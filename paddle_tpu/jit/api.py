"""jit.api (reference: python/paddle/jit/api.py).

The execution model IS trace-once/compile on TPU, so to_static is a thin
adapter: Layer forward → functional_call → jax.jit with donated params.
"""
from __future__ import annotations

import functools
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, Parameter, unwrap
from ..nn.layer.layers import Layer

_to_static_enabled = True


def enable_to_static(flag=True):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def disable_static():
    pass  # dynamic mode is the only mode; parity shim


def enable_static():
    pass  # static graph API served via paddle_tpu.static facade


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = shape
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


class StaticFunction:
    """Compiled callable wrapping a Layer method or plain function."""

    def __init__(self, function, input_spec=None, layer=None, **kwargs):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = {}
        functools.update_wrapper(self, function)

    def _key(self, args):
        def sig(a):
            if isinstance(a, Tensor):
                return ("T", tuple(a.shape), str(a.dtype))
            if isinstance(a, (jnp.ndarray, np.ndarray)):
                return ("A", tuple(a.shape), str(a.dtype))
            return ("S", a if isinstance(a, (int, float, str, bool, type(None)))
                    else str(type(a)))
        return tuple(sig(a) for a in args)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._function(*args, **kwargs)
        layer = self._layer
        if layer is None and args and isinstance(args[0], Layer):
            layer = args[0]
            args = args[1:]
        if layer is None:
            # plain function: jit over raw arrays
            key = self._key(args)
            if key not in self._jitted:
                fn = self._function

                def pure(*raws):
                    wrapped = [Tensor(r) if isinstance(r, jax.Array) else r
                               for r in raws]
                    out = fn(*wrapped, **kwargs)
                    return jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda t: isinstance(t, Tensor))
                self._jitted[key] = jax.jit(pure)
            raws = tuple(unwrap(a) if isinstance(a, Tensor) else a for a in args)
            out = self._jitted[key](*raws)
            return jax.tree_util.tree_map(Tensor, out)
        # Layer method: functional over (params, buffers, inputs)
        key = self._key(args)
        if key not in self._jitted:
            fn = self._function

            def pure(params, buffers, *raws):
                wrapped = [Tensor(r) if isinstance(r, jax.Array) else r
                           for r in raws]
                with layer._swapped_state(params, buffers):
                    out = fn(layer, *wrapped, **kwargs) if _is_method(fn) else \
                        fn(*wrapped, **kwargs)
                return jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            self._jitted[key] = jax.jit(pure)
        params, buffers = layer.functional_state()
        raws = tuple(unwrap(a) if isinstance(a, Tensor) else a for a in args)
        out = self._jitted[key](params, buffers, *raws)
        return jax.tree_util.tree_map(Tensor, out)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._function)


def _is_method(fn):
    import inspect
    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    def decorate(fn_or_layer):
        if isinstance(fn_or_layer, Layer):
            layer = fn_or_layer
            layer.forward = StaticFunction(layer.forward.__func__
                                           if hasattr(layer.forward, "__func__")
                                           else layer.forward,
                                           input_spec, layer=layer)
            return layer
        return StaticFunction(fn_or_layer, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer(Layer):
    """Loaded inference layer (reference: translated_layer.py)."""

    def __init__(self, state, forward_fn):
        super().__init__()
        self._state = state
        self._forward_fn = forward_fn

    def forward(self, *args):
        return self._forward_fn(*args)


def save(layer, path, input_spec=None, **configs):
    """Serialize params + class info. XLA AOT export is the deployment
    path on TPU (round 2: jax.export)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        raise TypeError("save a Layer, not a StaticFunction")
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__name__, "module": type(layer).__module__}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    import importlib
    try:
        mod = importlib.import_module(meta["module"])
        cls = getattr(mod, meta["class"])
        try:
            layer = cls()
            layer.set_state_dict({k: Tensor(jnp.asarray(v))
                                  for k, v in state.items()})
            return layer
        except TypeError:
            pass
    except Exception:
        pass
    state_t = {k: Tensor(jnp.asarray(v)) for k, v in state.items()}
    return TranslatedLayer(state_t, lambda *a: (_ for _ in ()).throw(
        RuntimeError("TranslatedLayer: reconstruct the original class to run")))
