"""TPU pallas kernels that are engine-shaped rather than op-shaped.

`paddle_tpu.ops` holds kernels with framework-level contracts (flash
attention, paged decode/verify attention); this package holds kernels
written against the serving engine's own data layout — currently the
ragged paged-attention core behind `unified_step` (docs/serving.md
§ Unified ragged step). CPU sessions import only the pure-jnp
reference path; the pallas lowering is reached on TPU or under
interpret mode in tests.
"""
from .ragged_paged_attention import (ragged_paged_attention,
                                     ragged_paged_attention_reference)

__all__ = ["ragged_paged_attention", "ragged_paged_attention_reference"]
