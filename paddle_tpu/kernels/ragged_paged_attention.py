"""Ragged paged attention: one kernel for an arbitrary prefill/decode mix.

The serving engine's `unified_step` feeds a FLAT token buffer — every
row is one token of some sequence, described by `(tok_slot, tok_pos)`
instead of a (batch, seq) grid — so a single device program serves any
mix of prefill chunks, prefix-cache suffix tails, spec-verify grids and
single-token decodes ("Ragged Paged Attention", PAPERS.md; the
split-fuse / fixed-token-budget direction). Row i attends over slot
`tok_slot[i]`'s paged KV through the page table, causally limited to
columns `< tok_pos[i] + 1` (its own position included — the row's K/V
was scattered into the pages beforehand). Inactive buffer slack rows
carry `tok_pos = -1`: every page is skipped for them, which is the
attention early-exit that makes the fixed buffer cheap.

Two implementations with ONE arithmetic contract, asserted BIT-identical
on CPU in tests. Bit-exactness across two separately-compiled XLA
programs does not come for free — three things make it hold:

  * both run the SAME traced op sequence: `_page_update` below is the
    single online-softmax page step, called from the pallas kernel body
    and from the reference's page scan;
  * the reference replays the kernel's exact operand SHAPES (q group
    padded to the sublane minimum, m/l stats lane-replicated to
    (group_pad, LANES) with `_fit_lanes` slicing) — XLA CPU picks
    different vectorizations for different shapes and e.g. `exp` then
    rounds differently;
  * `lax.optimization_barrier` pins the contraction-sensitive spots
    (the dots, the exps, each mul feeding an add) so neither compiled
    loop body can FMA/fuse them into differently-rounded forms. The
    barrier has no vmap batching rule, so the reference fans out over
    (token, head) with `lax.map` rather than vmap.

GQA: q is viewed (tokens, kv_heads, group, head_dim). int8 pools ride
per-token fp32 scales dequantized inside `_page_update`.

Tile shape is a STATIC parameter (`block_q` q-rows per block x
`block_pages` KV pages per grid step, both sublane-legal), defaulting
to the seed shape (GQA group padded to the sublane minimum x 1 page).
Every legal config runs the identical `_page_update` call sequence over
the same page ordinals with the same operand shapes, so the jnp
reference stays the bit-identity oracle for all of them — what changes
is only how the pallas grid batches DMA and compute. The per-TPU-
generation winner is found offline by tools/tune_ragged.py and loaded
through paddle_tpu/_tuning_defaults.load_ragged_tile.
"""
from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.flash_attention import _fit_lanes
from ..ops.paged_attention import LANES, MIN_GROUP, NEG_INF, Z, _on_tpu

__all__ = ["ragged_paged_attention", "ragged_paged_attention_reference"]

_bar = jax.lax.optimization_barrier


def _page_update(q, k, v, acc, m_prev, l_prev, limit, pi, scale,
                 page_size, ks=None, vs=None):
    """One online-softmax step over one KV page — THE arithmetic
    contract shared by the pallas kernel and the jnp reference.

    q/acc: (group_pad, d) f32; m_prev/l_prev: (group_pad, LANES) f32;
    k/v: (page_size, d) f32; ks/vs: (page_size, 1) dequant scales when
    the pool is int8; limit/pi: i32 scalars. Returns the updated
    (acc, m, l). The optimization barriers keep XLA from contracting
    the muls into the adds (or re-fusing the dots/exps) differently in
    the two compiled programs — without them the kernel and reference
    drift by 1 ULP on CPU.
    """
    if ks is not None:
        k = k * ks
        v = v * vs
    s = _bar(jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)) * scale
    cols = pi * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < limit, s, NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = _bar(jnp.exp(s - _fit_lanes(m_new, s.shape[-1])))
    alpha = _bar(jnp.exp(m_prev - m_new))
    al, sp = _bar((alpha * l_prev, jnp.sum(p, axis=1, keepdims=True)))
    l_new = al + sp
    aa, pv = _bar((acc * _fit_lanes(alpha, acc.shape[-1]),
                   jax.lax.dot_general(
                       p, v, (((1,), (0,)), ((), ())),
                       preferred_element_type=jnp.float32)))
    return aa + pv, m_new, l_new


# ---------------------------------------------------------------------------
# Reference (pure jnp, CPU production path)
# ---------------------------------------------------------------------------
def ragged_paged_attention_reference(q, k_pages, v_pages, page_table,
                                     tok_slot, tok_pos, sm_scale=None,
                                     k_scale=None, v_scale=None,
                                     block_q=None):
    """q: (T, QH, D); pages: (KVH, P, page, D); page_table:
    (S, pages_per_seq); tok_slot/tok_pos: (T,) i32 (pos -1 = inactive
    row → zeros out). Returns (T, QH, D).

    This is NOT a dense-softmax shortcut: it replays `_page_update`
    over page ordinals with the kernel's exact shapes (group padded,
    lane-replicated stats), skipped pages carrying the previous stats
    through unchanged, so CPU tests can assert the pallas kernel
    bit-identical against it. `block_q` is the kernel's q-row block
    (the q group's sublane padding) — the reference must replay the
    same padded shape to stay the bit-identity oracle for a non-default
    tile. `block_pages` has no reference twin: it only re-batches the
    grid, the `_page_update` ordinal sequence is unchanged."""
    t, qh, d = q.shape
    kvh, _, page_size, _ = k_pages.shape
    group = qh // kvh
    gp = _resolve_block_q(block_q, group)
    scale = np.float32(sm_scale if sm_scale is not None else d ** -0.5)
    n_pages = page_table.shape[1]
    quant = k_scale is not None

    pages = page_table[tok_slot].astype(jnp.int32)       # (T, n_pages)
    limit = (tok_pos + 1).astype(jnp.int32)              # (T,)
    qg = q.reshape(t, kvh, group, d).astype(jnp.float32)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    def token_head(args):
        qg_th, pages_t, limit_t, hi = args
        k_h = k_pages[hi]
        v_h = v_pages[hi]
        sc = (k_scale[hi], v_scale[hi]) if quant else None

        def body(carry, xs):
            acc, m, l = carry
            pg, pi = xs
            k = k_h[pg].astype(jnp.float32)              # (page, d)
            v = v_h[pg].astype(jnp.float32)
            acc_new, m_new, l_new = _page_update(
                qg_th, k, v, acc, m, l, limit_t, pi, scale, page_size,
                *( (sc[0][pg], sc[1][pg]) if quant else () ))
            # page skip: the kernel's @pl.when leaves the scratch
            # UNTOUCHED on a masked page — carry the old bits through
            take = pi * page_size < limit_t
            return (jnp.where(take, acc_new, acc),
                    jnp.where(take, m_new, m),
                    jnp.where(take, l_new, l)), None

        init = (jnp.zeros((gp, d), jnp.float32),
                jnp.full((gp, LANES), NEG_INF, jnp.float32),
                jnp.zeros((gp, LANES), jnp.float32))
        (acc, m, l), _ = jax.lax.scan(
            body, init, (pages_t, jnp.arange(n_pages, dtype=jnp.int32)))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return acc / _fit_lanes(l_safe, acc.shape[-1])

    ti_idx = jnp.repeat(jnp.arange(t), kvh)
    hi_idx = jnp.tile(jnp.arange(kvh), t)
    o = jax.lax.map(token_head, (qg.reshape(t * kvh, gp, d),
                                 pages[ti_idx], limit[ti_idx], hi_idx))
    o = o.reshape(t, kvh, gp, d)[:, :, :group]
    return o.reshape(t, qh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------
def _resolve_block_q(block_q, group):
    """Validated q-row block: None/0 derive the seed shape (group
    padded to the sublane minimum); an explicit value must cover the
    group and stay sublane-aligned or the block is not DMA-legal."""
    gp_min = group + (-group) % MIN_GROUP
    if not block_q:
        return gp_min
    block_q = int(block_q)
    if block_q % MIN_GROUP or block_q < group:
        raise ValueError(
            f"block_q={block_q}: must be a multiple of the sublane "
            f"tile ({MIN_GROUP}) and >= the GQA group ({group})")
    return block_q


def _ragged_kernel(slot_ref, pos_ref, ptab_ref, *refs, scale, page_size,
                   n_pages, block_pages, quant):
    """Grid (T, KVH, ceil(pages_per_seq / block_pages));
    tok_slot/tok_pos/page_table ride scalar prefetch — each of the
    `block_pages` per-step page operands has its own BlockSpec index
    map resolving `ptab[slot[ti], pi*block_pages + j]`, so one grid
    step DMAs a strip of `block_pages` pages and the unrolled body
    consumes them in ordinal order (the exact `_page_update` sequence
    of the one-page kernel — bit-identity is tile-invariant). Scale
    refs ride interleaved per page when the pool is int8, dequantized
    inside `_page_update` so int8 is what rides HBM→VMEM."""
    del slot_ref, ptab_ref  # consumed by the index maps
    per = 4 if quant else 2
    q_ref = refs[0]
    page_refs = refs[1:1 + per * block_pages]
    o_ref = refs[1 + per * block_pages]
    acc_ref, m_ref, l_ref = refs[2 + per * block_pages:]
    ti = pl.program_id(0)
    pi = pl.program_id(2)
    grid_pages = -(-n_pages // block_pages)

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    limit = pos_ref[ti] + 1  # -1 (inactive row) → 0: every page skips

    for j in range(block_pages):
        # ordinal*page_size < limit also masks the clamped
        # past-the-end ordinals of the last grid step: limit <=
        # n_pages*page_size always, so ordinal >= n_pages fails it —
        # the same predicate the reference's `take` carry uses.
        ordinal = pi * block_pages + j
        k_ref = page_refs[per * j]
        v_ref = page_refs[per * j + 1]
        sc_refs = page_refs[per * j + 2:per * j + 4] if quant else None

        @pl.when(ordinal * page_size < limit)
        def _body(k_ref=k_ref, v_ref=v_ref, sc_refs=sc_refs,
                  ordinal=ordinal):
            sc = () if sc_refs is None else (sc_refs[0][0, 0],
                                             sc_refs[1][0, 0])
            acc_new, m_new, l_new = _page_update(
                q_ref[0, 0].astype(jnp.float32),
                k_ref[0, 0].astype(jnp.float32),
                v_ref[0, 0].astype(jnp.float32),
                acc_ref[:], m_ref[:], l_ref[:], limit, ordinal, scale,
                page_size, *sc)
            acc_ref[:] = acc_new
            m_ref[:] = m_new
            l_ref[:] = l_new

    @pl.when(pi == grid_pages - 1)
    def _fin():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] /
                       _fit_lanes(l_safe, o_ref.shape[-1])).astype(o_ref.dtype)


def _ragged_pallas(q4, k_pages, v_pages, page_table, tok_slot, tok_pos,
                   scale, interpret, k_scale=None, v_scale=None,
                   block_pages=1):
    t, kvh, group_pad, d = q4.shape
    _, _, page_size, _ = k_pages.shape
    n_pages = page_table.shape[1]
    quant = k_scale is not None
    grid_pages = -(-n_pages // block_pages)

    # index maps receive grid indices first, then scalar-prefetch refs.
    # Per-j maps pick page ordinal pi*block_pages + j, clamped on the
    # ragged last strip (the kernel body masks those ordinals out).
    def _page_map(j):
        def m(ti, hi, pi, slot, pos, ptab):
            o = jnp.minimum(pi * block_pages + j, n_pages - 1)
            return (hi, ptab[slot[ti], o], Z, Z)
        return m

    in_specs = [
        pl.BlockSpec((1, 1, group_pad, d),
                     lambda ti, hi, pi, slot, pos, ptab: (ti, hi, Z, Z)),
    ]
    operands = [tok_slot, tok_pos, page_table, q4]
    for j in range(block_pages):
        page_spec = pl.BlockSpec((1, 1, page_size, d), _page_map(j))
        in_specs += [page_spec, page_spec]
        operands += [k_pages, v_pages]
        if quant:
            scale_spec = pl.BlockSpec((1, 1, page_size, 1), _page_map(j))
            in_specs += [scale_spec, scale_spec]
            operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t, kvh, grid_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group_pad, d),
                               lambda ti, hi, pi, slot, pos, ptab:
                               (ti, hi, Z, Z)),
        scratch_shapes=[
            pltpu.VMEM((group_pad, d), jnp.float32),
            pltpu.VMEM((group_pad, LANES), jnp.float32),
            pltpu.VMEM((group_pad, LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel, scale=np.float32(scale), page_size=page_size,
        n_pages=n_pages, block_pages=block_pages, quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, kvh, group_pad, d), q4.dtype),
        interpret=interpret,
    )(*operands)


def ragged_paged_attention(q, k_pages, v_pages, page_table, tok_slot,
                           tok_pos, sm_scale=None, use_pallas=None,
                           interpret=None, k_scale=None, v_scale=None,
                           block_q=None, block_pages=None):
    """Ragged mixed prefill/decode attention over a paged KV cache.

    q: (T, QH, D) — T flat token rows; k_pages/v_pages:
    (KVH, num_pages, page_size, D); page_table: (S, pages_per_seq)
    i32; tok_slot: (T,) i32 owning slot per row; tok_pos: (T,) i32
    absolute position per row (-1 = inactive slack row → zero output).
    Row i attends to slot tok_slot[i]'s cache columns < tok_pos[i]+1.

    int8 cache: pass int8 pages plus k_scale/v_scale fp32 per-token
    scales (KVH, num_pages, page_size, 1), dequantized inside the
    kernel. Off-TPU (and not under interpret) the jnp reference runs —
    same arithmetic, bit-identical.

    `block_q`/`block_pages` pick the STATIC kernel tile (q rows per
    block x KV pages per grid step); None/0 keep the seed defaults
    (sublane-padded group x 1). Any legal tile computes the same
    `_page_update` sequence — outputs stay bit-identical to the
    reference at the matching `block_q` — so the choice is purely a
    DMA/occupancy trade tuned per TPU generation (tools/tune_ragged.py,
    docs/tuning.md § Kernel autotune).
    """
    t, qh, d = q.shape
    kvh = k_pages.shape[0]
    group = qh // kvh
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    gp = _resolve_block_q(block_q, group)
    n_pages = page_table.shape[1]
    bp = int(block_pages or 1)
    if bp < 1:
        raise ValueError(f"block_pages={block_pages}: want >= 1")
    bp = min(bp, n_pages)
    if use_pallas is None:
        use_pallas = _on_tpu()
    if interpret is None:
        interpret = False
    if not use_pallas and not interpret:
        return ragged_paged_attention_reference(
            q, k_pages, v_pages, page_table, tok_slot, tok_pos, scale,
            k_scale, v_scale, block_q=gp)
    q4 = q.reshape(t, kvh, group, d)
    pad = gp - group
    if pad:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, pad), (0, 0)))
    o = _ragged_pallas(q4, k_pages, v_pages,
                       page_table.astype(jnp.int32),
                       tok_slot.astype(jnp.int32),
                       tok_pos.astype(jnp.int32), scale, interpret,
                       k_scale=k_scale, v_scale=v_scale, block_pages=bp)
    if pad:
        o = o[:, :, :group]
    return o.reshape(t, qh, d)
