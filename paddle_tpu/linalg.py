"""paddle.linalg namespace (reference: python/paddle/linalg.py)."""
from .tensor.linalg import (  # noqa: F401
    matmul, mm, bmm, dot, norm, dist, cross, cholesky, cholesky_solve, inv,
    qr, svd, svdvals, eig, eigh, eigvals, eigvalsh, solve, lstsq, matrix_power,
    matrix_rank, triangular_solve, pinv, slogdet, det, mv, multi_dot, cov,
    corrcoef, lu, lu_unpack, householder_product, matrix_exp, vecdot, cdist,
    matrix_transpose, ormqr, vector_norm, matrix_norm, cond,
    cholesky_inverse, svd_lowrank, pca_lowrank, histogram_bin_edges,
)
from .tensor.math import vander  # noqa: F401
from .tensor.creation import diagonal  # noqa: F401


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="bfloat16", activation_type=None):
    """reference: linalg fp8 GEMM (CUDA cutlass kernel). TPU path: cast
    fp8 operands up, run the MXU matmul with fp32 accumulation, apply
    scale/bias/activation, emit bf16/fp16. On fp8-capable TPU gens XLA
    keeps the low-precision layout."""
    import jax
    import jax.numpy as jnp
    from ._core.tensor import apply

    def fn(a, b, *rest):
        bb = rest[0] if bias is not None else None
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        out = out * scale
        if bb is not None:
            out = out + bb.astype(jnp.float32)
        if activation_type in ("gelu",):
            out = jax.nn.gelu(out)
        elif activation_type in ("relu",):
            out = jax.nn.relu(out)
        return out.astype(jnp.bfloat16 if output_dtype == "bfloat16"
                          else jnp.float16)

    args = [x, y] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="fp8_fp8_half_gemm_fused")
