"""paddle.linalg namespace (reference: python/paddle/linalg.py)."""
from .tensor.linalg import (  # noqa: F401
    matmul, mm, bmm, dot, norm, dist, cross, cholesky, cholesky_solve, inv,
    qr, svd, svdvals, eig, eigh, eigvals, eigvalsh, solve, lstsq, matrix_power,
    matrix_rank, triangular_solve, pinv, slogdet, det, mv, multi_dot, cov,
    corrcoef, lu, lu_unpack, householder_product, matrix_exp, vecdot, cdist,
    matrix_transpose, ormqr, vector_norm, matrix_norm, cond,
    cholesky_inverse, svd_lowrank, pca_lowrank, histogram_bin_edges,
)
from .tensor.math import vander  # noqa: F401
