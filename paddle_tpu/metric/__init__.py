"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, unwrap


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = np.asarray(unwrap(pred))
        l = np.asarray(unwrap(label))
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        if l.ndim == p.ndim:  # one-hot
            l = l.argmax(-1)
        topk_idx = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = topk_idx == l[..., None]
        return Tensor(jnp.asarray(correct.astype(np.float32)))

    def update(self, correct, *args):
        c = np.asarray(unwrap(correct))
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            tot = int(np.prod(c.shape[:-1]))
            self.total[i] += float(num)
            self.count[i] += tot
            accs.append(float(num) / max(tot, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).round().astype(np.int32).reshape(-1)
        l = np.asarray(unwrap(labels)).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).round().astype(np.int32).reshape(-1)
        l = np.asarray(unwrap(labels)).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kw):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(unwrap(labels)).reshape(-1)
        idx = (p * self.num_thresholds).astype(np.int64)
        idx = np.clip(idx, 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds descending
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") else \
            float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    p = np.asarray(unwrap(input))
    l = np.asarray(unwrap(label)).reshape(-1)
    topk_idx = np.argsort(-p, axis=-1)[:, :k]
    corr = (topk_idx == l[:, None]).any(axis=1).mean()
    return Tensor(jnp.asarray(np.float32(corr)))
