"""Model families (flagship workloads from BASELINE.json configs)."""
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM  # noqa: F401
from . import llama_spmd  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification, BertForPretraining,
)
from .gpt2 import GPT2Config, GPT2Model, GPT2LMHeadModel  # noqa: F401
from .moe_llm import MoEConfig, MoEForCausalLM  # noqa: F401
from .qwen2 import Qwen2Config, Qwen2Model, Qwen2ForCausalLM  # noqa: F401
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForSequenceClassification,
    ErnieForTokenClassification, ErnieForPretraining,
)
from .deepseek import DeepSeekConfig, DeepSeekForCausalLM  # noqa: F401
from . import generation  # noqa: F401
