"""BERT (reference workload: PaddleNLP bert finetune — BASELINE config 3).

Standard pre-LN-free BERT encoder built on paddle_tpu.nn primitives;
attention path uses the fused scaled_dot_product_attention (flash kernel
when unmasked).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal, Constant


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128)


class BertEmbeddings(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        init = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(s, dtype="int64")
        if token_type_ids is None:
            from ..tensor.creation import zeros
            token_type_ids = zeros([input_ids.shape[0], s], dtype="int64")
        emb = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids) + \
            self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        c = config
        self.embeddings = BertEmbeddings(c)
        layer = nn.TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob, act_dropout=0.0,
            normalize_before=False, layer_norm_eps=c.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(layer, c.num_hidden_layers)
        self.pooler = nn.Linear(c.hidden_size, c.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # (B, S) 1/0 → additive (B, 1, 1, S)
            def fn(m):
                return (1.0 - m.astype(jnp.float32))[:, None, None, :] * -1e4
            attention_mask = apply(fn, attention_mask, name="bert_mask")
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels), logits
        return logits


class BertLMHead(nn.Layer):
    def __init__(self, config: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.decoder_weight = embedding_weights
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True,
            default_initializer=Constant(0.0))
        self.act = config.hidden_act

    def forward(self, hidden):
        h = getattr(F, self.act)(self.transform(hidden))
        h = self.layer_norm(h)
        from ..tensor.linalg import matmul
        return matmul(h, self.decoder_weight, transpose_y=True) + \
            self.decoder_bias


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference: PaddleNLP BertForPretraining)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.cls_mlm = BertLMHead(config,
                                  self.bert.embeddings.word_embeddings.weight)
        self.cls_nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        mlm_logits = self.cls_mlm(seq)
        nsp_logits = self.cls_nsp(pooled)
        if masked_lm_labels is not None:
            mlm_loss = F.cross_entropy(mlm_logits, masked_lm_labels,
                                       ignore_index=-100)
            loss = mlm_loss
            if next_sentence_label is not None:
                loss = loss + F.cross_entropy(nsp_logits, next_sentence_label)
            return loss, mlm_logits, nsp_logits
        return mlm_logits, nsp_logits
