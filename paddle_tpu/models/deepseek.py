"""DeepSeek-V2/V3-style model: MLA attention + DeepSeekMoE FFN.

Reference parity: PaddleNLP paddlenlp/transformers/deepseek_v2 modeling
(the reference fork's era ships DeepSeek support as a flagship family).
TPU-native design notes:

  * **MLA (Multi-head Latent Attention)**: K/V are generated from a
    low-rank latent `c_kv = x·W_dkv` (dim kv_lora_rank ≪ H), plus a
    decoupled RoPE branch of dim qk_rope_head_dim shared across heads.
    The latent is what a serving cache would store — cache bytes drop by
    ~an order of magnitude vs full K/V. Projections are plain matmuls
    (MXU); attention runs through our flash kernel after up-projection.
  * **MoE FFN**: shared experts + routed experts with top-k gating and
    the load-balance aux loss, reusing parallel.moe's EP dispatch.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from .._core.tensor import Tensor, apply
from ..nn.initializer import Normal
from ..ops.flash_attention import flash_attention_bhsd
from ..ops.rope import rope_cos_sin
from .llama import LlamaConfig, LlamaMLP
from .moe_llm import MoEDecoderLayer


@dataclass(unsafe_hash=True)
class DeepSeekConfig(LlamaConfig):
    kv_lora_rank: int = 512
    q_lora_rank: int = 0              # 0 = full-rank q projection
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    n_routed_experts: int = 8
    n_shared_experts: int = 1
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 0    # 0 = intermediate_size
    first_k_dense_replace: int = 1    # leading dense layers before MoE
    aux_loss_alpha: float = 0.001

    @classmethod
    def tiny_mla(cls, vocab=128, hidden=64, layers=2, heads=4):
        return cls(vocab_size=vocab, hidden_size=hidden,
                   intermediate_size=hidden * 2, num_hidden_layers=layers,
                   num_attention_heads=heads, num_key_value_heads=heads,
                   kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
                   v_head_dim=16, n_routed_experts=4, n_shared_experts=1,
                   num_experts_per_tok=2, moe_intermediate_size=hidden,
                   max_position_embeddings=256)


class MLAttention(nn.Layer):
    """Multi-head latent attention. Shapes:

    q:        x → (B,S,H·(d_nope+d_rope))   [optionally via q LoRA]
    latent:   x → c_kv (B,S,r) ⊕ k_rope (B,S,d_rope)   ← the cacheable part
    k,v:      c_kv → per-head k_nope (d_nope), v (d_v); k = [k_nope;k_rope]
    """

    def __init__(self, config: DeepSeekConfig):
        super().__init__()
        c = config
        self.nh = c.num_attention_heads
        self.d_nope = c.qk_nope_head_dim
        self.d_rope = c.qk_rope_head_dim
        self.d_v = c.v_head_dim
        self.r = c.kv_lora_rank
        H = c.hidden_size
        init = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        qd = self.nh * (self.d_nope + self.d_rope)
        self.q_proj = nn.Linear(H, qd, weight_attr=init, bias_attr=False)
        # latent: compressed kv + shared rope key
        self.kv_down = nn.Linear(H, self.r + self.d_rope, weight_attr=init,
                                 bias_attr=False)
        self.kv_norm = nn.RMSNorm(self.r, epsilon=c.rms_norm_eps)
        self.kv_up = nn.Linear(self.r, self.nh * (self.d_nope + self.d_v),
                               weight_attr=init, bias_attr=False)
        self.o_proj = nn.Linear(self.nh * self.d_v, H, weight_attr=init,
                                bias_attr=False)
        self.rope_theta = c.rope_theta

    def forward(self, x, cos, sin):
        b, s, H = x.shape
        nh, dn, dr, dv, r = self.nh, self.d_nope, self.d_rope, self.d_v, \
            self.r

        def fn(xr, wq, wdown, gnorm, wup, wo, cosr, sinr):
            q = (xr @ wq).reshape(b, s, nh, dn + dr)
            q_nope, q_rope = q[..., :dn], q[..., dn:]
            down = xr @ wdown                          # (B,S,r+dr)
            c_kv, k_rope = down[..., :r], down[..., r:]
            cf = c_kv.astype(jnp.float32)
            c_kv = (cf * jax.lax.rsqrt(
                jnp.mean(cf * cf, -1, keepdims=True) + 1e-5) *
                gnorm.astype(jnp.float32)).astype(xr.dtype)
            kv = (c_kv @ wup).reshape(b, s, nh, dn + dv)
            k_nope, v = kv[..., :dn], kv[..., dn:]

            def rot(t, cos_, sin_):
                half = t.shape[-1] // 2
                t1, t2 = t[..., :half], t[..., half:]
                rot_t = jnp.concatenate([-t2, t1], axis=-1)
                return t * cos_ + rot_t * sin_

            # decoupled rope: q per head, k shared across heads
            q_rope = rot(q_rope, cosr[None, :, None], sinr[None, :, None])
            k_rope = rot(k_rope, cosr[None], sinr[None])
            k_rope_h = jnp.broadcast_to(k_rope[:, :, None],
                                        (b, s, nh, dr))
            qh = jnp.concatenate([q_nope, q_rope], -1).swapaxes(1, 2)
            kh = jnp.concatenate([k_nope, k_rope_h], -1).swapaxes(1, 2)
            vh = v.swapaxes(1, 2)
            # pad v head dim to match qk dim for the kernel, slice after
            if dv < dn + dr:
                vh = jnp.pad(vh, ((0, 0),) * 3 + ((0, dn + dr - dv),))
            # static python float: sm_scale is a nondiff argnum of the pallas
            # custom_vjp — a traced array would fail under jit on TPU
            o = flash_attention_bhsd(qh, kh, vh, causal=True,
                                     sm_scale=1.0 / math.sqrt(dn + dr))
            o = o[..., :dv].swapaxes(1, 2).reshape(b, s, nh * dv)
            return o @ wo

        return apply(fn, x, self.q_proj.weight, self.kv_down.weight,
                     self.kv_norm.weight, self.kv_up.weight,
                     self.o_proj.weight, Tensor(cos), Tensor(sin),
                     name="mla_attention")


class DeepSeekDecoderLayer(nn.Layer):
    def __init__(self, config: DeepSeekConfig, layer_idx: int):
        super().__init__()
        c = config
        self.input_layernorm = nn.RMSNorm(c.hidden_size,
                                          epsilon=c.rms_norm_eps)
        self.self_attn = MLAttention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   epsilon=c.rms_norm_eps)
        if layer_idx < c.first_k_dense_replace:
            self.mlp = LlamaMLP(c)
            self.is_moe = False
        else:
            from ..parallel.moe import MoELayer
            inter = c.moe_intermediate_size or c.intermediate_size
            self.mlp = MoELayer(c.hidden_size, inter,
                                num_experts=c.n_routed_experts,
                                top_k=c.num_experts_per_tok,
                                num_shared_experts=c.n_shared_experts)
            self.is_moe = True

    def forward(self, x, cos, sin):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin)
        m = self.mlp(self.post_attention_layernorm(h))
        if isinstance(m, tuple):
            m = m[0]
        return h + m


class DeepSeekForCausalLM(nn.Layer):
    def __init__(self, config: DeepSeekConfig):
        super().__init__()
        c = self.config = config
        init = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.embed_tokens = nn.Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=init)
        self.layers = nn.LayerList([DeepSeekDecoderLayer(c, i)
                                    for i in range(c.num_hidden_layers)])
        self.norm = nn.RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        self.lm_head = nn.Linear(c.hidden_size, c.vocab_size,
                                 weight_attr=init, bias_attr=False)

    def forward(self, input_ids, labels=None):
        from ..nn import functional as F
        c = self.config
        s = input_ids.shape[1]
        cos, sin = rope_cos_sin(s, c.qk_rope_head_dim, base=c.rope_theta)
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, cos, sin)
        logits = self.lm_head(self.norm(x))
        if labels is not None:
            loss = F.cross_entropy(logits, labels, reduction="mean")
            return loss, logits
        return logits
