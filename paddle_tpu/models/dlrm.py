"""DLRM-style recommendation model over host-RAM sparse tables.

Reference parity: the reference's rec-sys stack — PaddleRec models
driven by paddle.distributed.ps (the_one_ps.py) with
paddle.static.nn.sparse_embedding feature tables — is WHY the PS tier
exists. TPU-native split:

  * sparse feature embeddings live in host-RAM SparseTable shards
    (distributed/ps_impl.py — beyond-HBM capacity, per-row optimizer),
    pulled per batch as plain inputs;
  * the dense tower (bottom MLP over dense features, pairwise feature
    interaction, top MLP) is a pure jitted function on device — its
    params train with any device optimizer;
  * one step = host pull → device fwd+bwd (grads for BOTH dense params
    and the pulled rows) → host push. No side effects under jit.

Model shape follows the standard DLRM: bottom MLP embeds dense
features to the embedding dim, dot-product interaction across all
(sparse + dense) feature vectors, top MLP on [dense_vec, interactions]
→ CTR logit.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp


def _mlp_params(rng, dims):
    ps = []
    for i in range(len(dims) - 1):
        scale = (2.0 / dims[i]) ** 0.5
        ps.append({"w": jnp.asarray(rng.randn(dims[i], dims[i + 1]) * scale,
                                    jnp.float32),
                   "b": jnp.zeros((dims[i + 1],), jnp.float32)})
    return ps


def _mlp(params, x, final_act=True):
    for i, lp in enumerate(params):
        x = x @ lp["w"] + lp["b"]
        if i < len(params) - 1 or final_act:
            x = jax.nn.relu(x)
    return x


class DLRMConfig:
    def __init__(self, emb_dim=16, n_sparse=8, dense_dim=13,
                 bottom=(64, 32), top=(64, 32)):
        self.emb_dim = emb_dim
        self.n_sparse = n_sparse          # sparse feature fields
        self.dense_dim = dense_dim        # continuous features
        self.bottom = tuple(bottom)
        self.top = tuple(top)


def init_dense_params(cfg: DLRMConfig, seed=0):
    """Device-side (tower) params; the embedding tables live in the PS."""
    rng = np.random.RandomState(seed)
    n_vec = cfg.n_sparse + 1              # + the bottom-MLP dense vector
    n_int = n_vec * (n_vec - 1) // 2      # upper-triangle interactions
    return {
        "bottom": _mlp_params(rng, (cfg.dense_dim,) + cfg.bottom
                              + (cfg.emb_dim,)),
        "top": _mlp_params(rng, (cfg.emb_dim + n_int,) + cfg.top + (1,)),
    }


def dlrm_forward(dense_params, emb_rows, dense_x, cfg: DLRMConfig):
    """emb_rows: (B, n_sparse, emb_dim) pulled rows; dense_x:
    (B, dense_dim). → logits (B,)."""
    dv = _mlp(dense_params["bottom"], dense_x)          # (B, E)
    vecs = jnp.concatenate([dv[:, None], emb_rows], 1)  # (B, F, E)
    inter = jnp.einsum("bfe,bge->bfg", vecs, vecs)      # (B, F, F)
    iu, ju = np.triu_indices(vecs.shape[1], k=1)
    feats = jnp.concatenate([dv, inter[:, iu, ju]], -1)
    return _mlp(dense_params["top"], feats,
                final_act=False)[..., 0]                # (B,)


def make_dlrm_step(cfg: DLRMConfig, lr=0.01):
    """Jitted (dense_params, unique_rows, inverse, dense_x, labels) →
    (new_dense_params, grad_unique_rows, loss). Dense tower trains with
    plain SGD in-step; the caller pushes grad_unique_rows to the PS
    (whose per-row rule may be sgd/adagrad/adam independently)."""

    @jax.jit
    def step(dense_params, rows, inv, dense_x, labels):
        def loss_fn(dp, r):
            emb = r[inv]                              # (B, n_sparse, E)
            logit = dlrm_forward(dp, emb, dense_x, cfg)
            return jnp.mean(
                jax.nn.softplus(jnp.where(labels > 0, -logit, logit)))
        loss, (g_dense, g_rows) = jax.value_and_grad(
            loss_fn, (0, 1))(dense_params, rows)
        new_dense = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, dense_params, g_dense)
        return new_dense, g_rows, loss

    return step


class DLRMTrainer:
    """Host loop wiring the PS pull/push around the jitted step.

    client: distributed.ps PSClient over the sparse tables (one shared
    table keyed by hashed (field, id) — the reference's distributed
    sparse_embedding convention); ids: (B, n_sparse) int64 feature ids
    (globally unique per field, e.g. pre-hashed with a field salt).
    """

    def __init__(self, cfg: DLRMConfig, client, seed=0, lr=0.01):
        from ..distributed.ps import DistributedEmbedding
        self.cfg = cfg
        self.emb = DistributedEmbedding(client, cfg.emb_dim)
        self.dense_params = init_dense_params(cfg, seed)
        self.step_fn = make_dlrm_step(cfg, lr=lr)

    def train_step(self, ids, dense_x, labels):
        rows, inv, uniq = self.emb.lookup(ids)
        # pad the unique-row axis to a power-of-two bucket: its length
        # is data-dependent (distinct ids per batch), and an unpadded
        # shape would trigger one XLA compile per distinct count
        U = len(uniq)
        cap = 1 << max(0, math.ceil(math.log2(max(U, 1))))
        if cap > U:
            rows = np.concatenate(
                [rows, np.zeros((cap - U, rows.shape[1]), rows.dtype)])
        self.dense_params, g_rows, loss = self.step_fn(
            self.dense_params, jnp.asarray(rows), jnp.asarray(inv),
            jnp.asarray(dense_x, jnp.float32),
            jnp.asarray(labels, jnp.float32))
        self.emb.apply_grads(uniq, np.asarray(g_rows)[:U])
        return float(loss)
