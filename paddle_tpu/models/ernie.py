"""ERNIE family — Paddle's flagship NLP pretrained models.

Reference workload: PaddleNLP ernie (ERNIE 1.0/3.0-style encoder:
BERT-architecture transformer whose pretraining uses knowledge/entity
masking; the network differs from BERT in config defaults, the
`task_type_embeddings` used by ERNIE 3.0, and relu feed-forward in
ERNIE 1.0). Built on the same paddle_tpu.nn encoder stack as models/
bert.py — TPU-first: one jittable pure function per head via
Layer.functional_state().
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor
from .. import nn
from ..nn import functional as F


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "relu"          # ERNIE 1.0 uses relu FFN
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 513
    type_vocab_size: int = 2
    task_type_vocab_size: int = 3     # ERNIE 3.0 task-type embedding
    use_task_id: bool = True
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128)


class ErnieEmbeddings(nn.Layer):
    """word + position + token-type (+ task-type) embeddings + LN."""

    def __init__(self, c: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size)
        self.use_task_id = c.use_task_id
        if c.use_task_id:
            self.task_type_embeddings = nn.Embedding(c.task_type_vocab_size,
                                                     c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size,
                                       epsilon=c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(
                input_ids._value if isinstance(input_ids, Tensor)
                else jnp.asarray(input_ids)))
        h = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids) \
            + self.token_type_embeddings(token_type_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = Tensor(jnp.zeros(
                    (input_ids.shape[0], s), jnp.int32))
            h = h + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(h))


class ErnieModel(nn.Layer):
    """reference: PaddleNLP ErnieModel — encoder + pooled [CLS] output."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        c = config
        self.config = c
        self.embeddings = ErnieEmbeddings(c)
        layer = nn.TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob,
            act_dropout=0.0, normalize_before=False)
        self.encoder = nn.TransformerEncoder(layer, c.num_hidden_layers)
        self.pooler = nn.Linear(c.hidden_size, c.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # (B, S) 1/0 keep-mask → additive (B, 1, 1, S); higher-rank
            # masks are assumed already additive (bert.py convention)
            def fn(m):
                return (1.0 - m.astype(jnp.float32))[:, None, None, :] \
                    * -1e4
            from .._core.tensor import apply
            attention_mask = apply(fn, attention_mask, name="ernie_mask")
        seq = self.encoder(h, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask, task_type_ids)
        return self.classifier(self.dropout(pooled))


class ErnieForTokenClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, **kw):
        seq, _ = self.ernie(input_ids, **kw)
        return self.classifier(self.dropout(seq))


# MLM head with tied input embeddings: identical machinery to BERT's
# (transform → act → LN → tied decode + bias); reuse it outright.
from .bert import BertLMHead as ErnieLMHead  # noqa: E402


class ErnieForPretraining(nn.Layer):
    """MLM (knowledge masking) + NSP, mirroring BertForPretraining."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.lm_head = ErnieLMHead(
            config, self.ernie.embeddings.word_embeddings.weight)
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None, **kw):
        seq, pooled = self.ernie(input_ids, token_type_ids,
                                 attention_mask=attention_mask, **kw)
        lm_logits = self.lm_head(seq)
        nsp_logits = self.nsp_head(pooled)
        if masked_lm_labels is None:
            return lm_logits, nsp_logits
        # -100-style ignore: positions with label < 0 excluded; NSP term
        # only when next_sentence_labels given (MLM-only pretrain is valid)
        from .._core.tensor import apply
        with_nsp = next_sentence_labels is not None

        def loss_fn(lm, lab, nsp, *rest):
            import jax
            lab = lab.astype(jnp.int32)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(lm.astype(jnp.float32), -1),
                jnp.clip(lab, 0)[..., None], -1)[..., 0]
            m = (lab >= 0).astype(jnp.float32)
            mlm = -jnp.sum(logp * m) / jnp.maximum(jnp.sum(m), 1.0)
            if not rest:
                return mlm
            nlogp = jax.nn.log_softmax(nsp.astype(jnp.float32), -1)
            nsp_l = -jnp.mean(jnp.take_along_axis(
                nlogp, rest[0].astype(jnp.int32)[:, None], -1))
            return mlm + nsp_l

        args = [lm_logits, masked_lm_labels, nsp_logits]
        if with_nsp:
            args.append(next_sentence_labels)
        return apply(loss_fn, *args, name="ernie_pretrain_loss")
