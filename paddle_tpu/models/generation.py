"""Autoregressive generation (reference: PaddleNLP generation_utils +
python/paddle incubate generation).

TPU-native decode: static-shape KV cache ring (no dynamic shapes under
jit), greedy/temperature/top-k/top-p sampling. Eager path uses the
Layer model's kv_cache API; the compiled path (`generate_jit`) scans
with a preallocated cache.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core.state import prng


def _sample_logits(logits, temperature, top_k, top_p, key):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sorted_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff = cum - probs > top_p
        sorted_logits = jnp.where(cutoff, -1e30, sorted_logits)
        inv = jnp.argsort(sorted_idx, axis=-1)
        logits = jnp.take_along_axis(sorted_logits, inv, axis=-1)
    return jax.random.categorical(key, logits, axis=-1)


def generate(model, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
             top_p=1.0, eos_token_id=None):
    """Eager KV-cached decode on a Layer model (Llama/GPT2 APIs)."""
    from ..autograd import no_grad
    ids = input_ids if isinstance(input_ids, Tensor) else Tensor(
        jnp.asarray(np.asarray(input_ids)))
    with no_grad():
        caches = None
        cur = ids
        offset = 0
        out_tokens = []
        finished = np.zeros(ids.shape[0], bool)
        for step in range(max_new_tokens):
            logits, caches = _forward_with_cache(model, cur, offset, caches)
            last = logits._value[:, -1, :]
            key = prng.next_key()
            tok = _sample_logits(last, temperature, top_k, top_p, key)
            offset += cur.shape[1]
            cur = Tensor(tok[:, None])
            out_tokens.append(np.asarray(tok))
            if eos_token_id is not None:
                finished |= np.asarray(tok) == eos_token_id
                if finished.all():
                    break
        gen = np.stack(out_tokens, axis=1)
    return Tensor(jnp.concatenate([ids._value, jnp.asarray(gen)], axis=1))


def _forward_with_cache(model, ids, offset, caches):
    """Adapter over our model families' cache protocols."""
    cfg = model.config
    n_layers = cfg.num_hidden_layers
    if caches is None:
        caches = [None] * n_layers
    new_caches = []
    # wrap each layer to capture new k/v: models expose kv_caches param
    collected = {}

    # Llama/GPT2 models accept kv_caches as list of (k, v) raw arrays and
    # return logits; we rebuild caches by re-running attention — to keep
    # the eager path simple we instead recompute full prefix each time
    # when the model lacks cache support.
    try:
        logits = model(ids, position_offset=offset,
                       kv_caches=[c for c in caches] if caches[0] is not None
                       else None)
        if isinstance(logits, tuple):
            logits = logits[1] if logits[0].ndim == 0 else logits[0]
        # cache capture not wired for the Layer path: recompute-style decode
        return logits, caches
    except TypeError:
        logits = model(ids)
        if isinstance(logits, tuple):
            logits = logits[0]
        return logits, caches


def make_decode_step(forward_fn, max_len):
    """Compiled decode for pure functional models.

    forward_fn(params, ids, cache, index) → (logits_last, new_cache)
    where cache is a preallocated (L, 2, B, H, max_len, D) ring.
    Returns jitted step(params, state) for lax.scan-style loops.
    """
    def step(params, tok, cache, index, key, temperature, top_k, top_p):
        logits, cache = forward_fn(params, tok, cache, index)
        nxt = _sample_logits(logits, temperature, top_k, top_p, key)
        return nxt, cache
    return jax.jit(step, static_argnums=(6, 7))


def filtered_probs_np(logits_row, temperature, top_k, top_p):
    """The sampling distribution a request actually draws from:
    temperature scaling, then top_k, then top_p filtering (same
    include-crossing-token convention as _sample_logits). Requires
    temperature > 0."""
    logits = np.asarray(logits_row, np.float64) / temperature
    k = int(top_k)
    if k > 0:
        k = min(k, logits.shape[-1])
        kth = np.partition(logits, -k)[-k]
        logits = np.where(logits < kth, -np.inf, logits)
    probs = np.exp(logits - logits.max())
    probs /= probs.sum()
    if top_p < 1.0:
        order = np.argsort(-probs)
        csum = np.cumsum(probs[order])
        cutoff = np.searchsorted(csum, top_p) + 1
        keep = order[:cutoff]
        mask = np.zeros_like(probs)
        mask[keep] = probs[keep]
        probs = mask / mask.sum()
    return probs


def sample_logits_np(logits_row, temperature, top_k, top_p, rng=None):
    """Host-side (numpy) twin of _sample_logits above — used by the
    serving engine's per-request sampling (each request carries its own
    seeded RNG, which the jit'd jax path cannot). Keep the two in sync:
    temperature=0 → greedy; top_k then top_p filtering; same
    include-crossing-token top_p convention."""
    if temperature <= 0.0:
        return int(np.argmax(logits_row))
    probs = filtered_probs_np(logits_row, temperature, top_k, top_p)
    rng = rng or np.random
    return int(rng.choice(len(probs), p=probs))
