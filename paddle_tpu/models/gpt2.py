"""GPT-2 style decoder with KV-cache generation (reference workload:
PaddleNLP gpt; exercises learned positions + pre-LN + causal attention).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops.flash_attention import flash_attention_bhsd


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.1
    initializer_range: float = 0.02

    @classmethod
    def tiny(cls):
        return cls(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128, dropout=0.0)


class GPT2Attention(nn.Layer):
    def __init__(self, c: GPT2Config):
        super().__init__()
        attr = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.n_head = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.c_attn = nn.Linear(c.hidden_size, 3 * c.hidden_size,
                                weight_attr=attr)
        self.c_proj = nn.Linear(c.hidden_size, c.hidden_size, weight_attr=attr)
        self.dropout = c.dropout

    def forward(self, x, kv_cache=None, causal=True):
        b, s, h = x.shape
        nh, hd = self.n_head, self.head_dim

        def fn(xr, w, bias, wo, bo, *cache):
            qkv = xr @ w + bias
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, nh, hd).swapaxes(1, 2)
            k = k.reshape(b, s, nh, hd).swapaxes(1, 2)
            v = v.reshape(b, s, nh, hd).swapaxes(1, 2)
            if cache:
                k = jnp.concatenate([cache[0], k], axis=2)
                v = jnp.concatenate([cache[1], v], axis=2)
            o = flash_attention_bhsd(q, k, v, causal=causal)
            o = o.swapaxes(1, 2).reshape(b, s, h)
            return o @ wo + bo, k, v

        args = [x, self.c_attn.weight, self.c_attn.bias, self.c_proj.weight,
                self.c_proj.bias]
        if kv_cache is not None:
            args += list(kv_cache)
        out, k, v = apply(fn, *args, name="gpt2_attention", multi=True)
        return out, (k, v)


class GPT2Block(nn.Layer):
    def __init__(self, c: GPT2Config):
        super().__init__()
        attr = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.ln_1 = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.attn = GPT2Attention(c)
        self.ln_2 = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.mlp_fc = nn.Linear(c.hidden_size, c.intermediate_size,
                                weight_attr=attr)
        self.mlp_proj = nn.Linear(c.intermediate_size, c.hidden_size,
                                  weight_attr=attr)
        self.drop = nn.Dropout(c.dropout)

    def forward(self, x, kv_cache=None, causal=True):
        a, new_cache = self.attn(self.ln_1(x), kv_cache, causal)
        x = x + self.drop(a)
        m = self.mlp_proj(F.gelu(self.mlp_fc(self.ln_2(x)), approximate=True))
        return x + self.drop(m), new_cache


class GPT2Model(nn.Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        c = config
        attr = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.wte = nn.Embedding(c.vocab_size, c.hidden_size, weight_attr=attr)
        self.wpe = nn.Embedding(c.max_position_embeddings, c.hidden_size,
                                weight_attr=attr)
        self.drop = nn.Dropout(c.dropout)
        self.h = nn.LayerList([GPT2Block(c) for _ in range(c.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)

    def forward(self, input_ids, position_offset=0, kv_caches=None):
        from ..tensor.creation import arange
        s = input_ids.shape[1]
        pos = arange(position_offset, position_offset + s, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        new_caches = []
        causal = s > 1
        for i, block in enumerate(self.h):
            cache = kv_caches[i] if kv_caches is not None else None
            x, nc = block(x, cache, causal=causal)
            new_caches.append(nc)
        return self.ln_f(x), new_caches


class GPT2LMHeadModel(nn.Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        self.transformer = GPT2Model(config)

    def forward(self, input_ids, labels=None, position_offset=0, kv_caches=None):
        h, new_caches = self.transformer(input_ids, position_offset, kv_caches)
        from ..tensor.linalg import matmul
        logits = matmul(h, self.transformer.wte.weight, transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        if kv_caches is not None or position_offset:
            return logits, new_caches
        return logits

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None):
        """KV-cached eager decode."""
        from ..autograd import no_grad
        from .generation import _sample_logits
        from .._core.state import prng
        ids = input_ids if isinstance(input_ids, Tensor) else \
            Tensor(jnp.asarray(np.asarray(input_ids)))
        with no_grad():
            logits, caches = self.forward(ids, position_offset=1)  # prefill
            toks = []
            cur_len = ids.shape[1]
            last = logits._value[:, -1]
            for step in range(max_new_tokens):
                tok = _sample_logits(last, temperature, top_k, top_p,
                                     prng.next_key())
                toks.append(np.asarray(tok))
                if eos_token_id is not None and \
                        (np.asarray(tok) == eos_token_id).all():
                    break
                cur = Tensor(tok[:, None])
                logits, caches = self.forward(cur, position_offset=cur_len,
                                              kv_caches=caches)
                cur_len += 1
                last = logits._value[:, -1]
        gen = jnp.asarray(np.stack(toks, axis=1))
        return Tensor(jnp.concatenate([ids._value, gen], axis=1))
