"""Llama-3 family (reference: PaddleNLP llm/ llama modeling — the
reference repo's north-star workload; structure mirrors
paddlenlp/transformers/llama/modeling.py but built TPU-first).

Two faces:
  * `LlamaForCausalLM` — paddle-style Layer tree (eager + jit-able).
  * `paddle_tpu.models.llama_spmd` — stacked-parameter pure-functional
    pretrain step with dp/pp/tp/sp shardings (the fleet 4D-parallel
    equivalent; used by bench + dryrun_multichip).

TPU choices: RMSNorm in fp32 accumulation, RoPE precomputed tables,
GQA flash attention (pallas), SwiGLU as one fused XLA graph, bf16
params with fp32 master weights in the optimizer.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .._core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops.rope import rope_cos_sin, apply_rotary_emb
from ..ops.flash_attention import flash_attention_bhsd


@dataclass(unsafe_hash=True)  # hashable → usable as a static jit arg
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    dtype: str = "float32"

    @classmethod
    def llama3_8b(cls):
        return cls(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                   num_hidden_layers=32, num_attention_heads=32,
                   num_key_value_heads=8, max_position_embeddings=8192,
                   rope_theta=500000.0)

    @classmethod
    def tiny(cls, vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, ffn=128,
             seq=128):
        return cls(vocab_size=vocab, hidden_size=hidden, intermediate_size=ffn,
                   num_hidden_layers=layers, num_attention_heads=heads,
                   num_key_value_heads=kv_heads, max_position_embeddings=seq)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig, tp_axis="tp"):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        init = Normal(0.0, c.initializer_range)
        h = c.hidden_size
        kv = self.num_kv_heads * self.head_dim
        self.q_proj = nn.Linear(h, h, weight_attr=nn.ParamAttr(initializer=init),
                                bias_attr=False)
        self.k_proj = nn.Linear(h, kv, weight_attr=nn.ParamAttr(initializer=init),
                                bias_attr=False)
        self.v_proj = nn.Linear(h, kv, weight_attr=nn.ParamAttr(initializer=init),
                                bias_attr=False)
        self.o_proj = nn.Linear(h, h, weight_attr=nn.ParamAttr(initializer=init),
                                bias_attr=False)
        # megatron TP: qkv column-parallel, o row-parallel
        for p in (self.q_proj.weight, self.k_proj.weight, self.v_proj.weight):
            p.dist_spec = P(None, tp_axis)
        self.o_proj.weight.dist_spec = P(tp_axis, None)

    def forward(self, x, cos, sin, kv_cache=None, causal=True):
        b, s, h = x.shape

        def fn(xr, wq, wk, wv, wo, cosr, sinr, *cache):
            q = (xr @ wq).reshape(b, s, self.num_heads, self.head_dim)
            k = (xr @ wk).reshape(b, s, self.num_kv_heads, self.head_dim)
            v = (xr @ wv).reshape(b, s, self.num_kv_heads, self.head_dim)
            # rope on (B, S, H, D): broadcast cos/sin over head axis
            q, k = apply_rotary_emb(q.swapaxes(1, 2), k.swapaxes(1, 2),
                                    cosr[None, None], sinr[None, None])
            v = v.swapaxes(1, 2)
            if cache:
                ck, cv = cache
                k = jnp.concatenate([ck, k], axis=2)
                v = jnp.concatenate([cv, v], axis=2)
            rep = self.num_heads // self.num_kv_heads
            if rep > 1:
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            o = flash_attention_bhsd(q, k, v, causal=causal)
            o = o.swapaxes(1, 2).reshape(b, s, h)
            return o @ wo

        args = [x, self.q_proj.weight, self.k_proj.weight, self.v_proj.weight,
                self.o_proj.weight, Tensor(cos), Tensor(sin)]
        if kv_cache is not None:
            args += [kv_cache[0], kv_cache[1]]
        return apply(fn, *args, name="llama_attention")


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig, tp_axis="tp"):
        super().__init__()
        c = config
        init = Normal(0.0, c.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.gate_proj = nn.Linear(c.hidden_size, c.intermediate_size,
                                   weight_attr=attr, bias_attr=False)
        self.up_proj = nn.Linear(c.hidden_size, c.intermediate_size,
                                 weight_attr=attr, bias_attr=False)
        self.down_proj = nn.Linear(c.intermediate_size, c.hidden_size,
                                   weight_attr=attr, bias_attr=False)
        self.gate_proj.weight.dist_spec = P(None, tp_axis)
        self.up_proj.weight.dist_spec = P(None, tp_axis)
        self.down_proj.weight.dist_spec = P(tp_axis, None)

    def forward(self, x):
        def fn(xr, wg, wu, wd):
            from ..ops.fused import fused_swiglu
            return fused_swiglu(xr, wg, wu, wd)
        return apply(fn, x, self.gate_proj.weight, self.up_proj.weight,
                     self.down_proj.weight, name="llama_mlp")


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cos, sin, kv_cache=None, causal=True):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, kv_cache,
                               causal)
        return h + self.mlp(self.post_attention_layernorm(h))


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.embed_tokens.weight.dist_spec = P("tp", None)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self._rope_cache = {}

    def rope(self, seq_len, dtype=jnp.float32, offset=0):
        key = (seq_len + offset, str(dtype))
        if key not in self._rope_cache:
            self._rope_cache[key] = rope_cos_sin(
                seq_len + offset, self.config.hidden_size //
                self.config.num_attention_heads, self.config.rope_theta, dtype)
        cos, sin = self._rope_cache[key]
        return cos[offset:], sin[offset:]

    def forward(self, input_ids, position_offset=0, kv_caches=None, causal=True):
        s = input_ids.shape[1]
        cos, sin = self.rope(s, offset=position_offset)
        x = self.embed_tokens(input_ids)
        for i, layer in enumerate(self.layers):
            cache = kv_caches[i] if kv_caches is not None else None
            x = layer(x, cos, sin, cache, causal)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=nn.ParamAttr(
                    initializer=Normal(0.0, config.initializer_range)),
                bias_attr=False)
            self.lm_head.weight.dist_spec = P(None, "tp")
        else:
            self.lm_head = None

    def forward(self, input_ids, labels=None, position_offset=0, kv_caches=None):
        h = self.llama(input_ids, position_offset, kv_caches)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            from ..tensor.linalg import matmul
            logits = matmul(h, self.llama.embed_tokens.weight, transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(logits, labels, reduction="mean")
            return loss, logits
        return logits

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None):
        from .generation import generate as _gen
        return _gen(self, input_ids, max_new_tokens, temperature, top_k, top_p,
                    eos_token_id)
