"""Compiled Llama decode: static-shape KV cache + lax.scan token loop.

The TPU inference path (reference: PaddleNLP predictor/fused generation
kernels): no dynamic shapes — the cache is a preallocated
(L, 2, B, KVH, max_len, D) ring written at position `index` via
dynamic_update_slice; attention masks keys beyond the current length.
One jit for prefill, one for the whole decode scan.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.rope import rope_cos_sin, apply_rotary_emb
from .llama import LlamaConfig


def init_cache(config: LlamaConfig, batch, max_len, dtype=jnp.float32):
    c = config
    hd = c.hidden_size // c.num_attention_heads
    return jnp.zeros((c.num_hidden_layers, 2, batch, c.num_key_value_heads,
                      max_len, hd), dtype)


def _layer_decode(lp, h, cache_layer, index, rope_full, config, prefill_len=None):
    """h: (B, S, H) (S=prompt len at prefill, 1 at decode).
    cache_layer: (2, B, KVH, max_len, D). index: write offset."""
    c = config
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    b, s, H = h.shape
    cos_f, sin_f = rope_full
    cos = lax.dynamic_slice_in_dim(cos_f, index, s, axis=0) if s == 1 else \
        cos_f[:s]
    sin = lax.dynamic_slice_in_dim(sin_f, index, s, axis=0) if s == 1 else \
        sin_f[:s]

    xf = h.astype(jnp.float32)
    x = (xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + c.rms_norm_eps)
         * lp["ln1"]).astype(h.dtype)
    q = (x @ lp["wq"]).reshape(b, s, nh, hd).swapaxes(1, 2)
    k = (x @ lp["wk"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
    v = (x @ lp["wv"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
    q, k = apply_rotary_emb(q, k, cos[None, None], sin[None, None])

    # write k/v into the ring at [index, index+s)
    new_k = lax.dynamic_update_slice(cache_layer[0], k.astype(cache_layer.dtype),
                                     (0, 0, index, 0))
    new_v = lax.dynamic_update_slice(cache_layer[1], v.astype(cache_layer.dtype),
                                     (0, 0, index, 0))
    cache_layer = jnp.stack([new_k, new_v])

    max_len = new_k.shape[-2]
    rep = nh // nkv
    kk = jnp.repeat(new_k, rep, axis=1) if rep > 1 else new_k
    vv = jnp.repeat(new_v, rep, axis=1) if rep > 1 else new_v
    scale = 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        kk.astype(jnp.float32)) * scale
    kpos = jnp.arange(max_len)[None, :]
    qpos = index + jnp.arange(s)[:, None]
    mask = kpos <= qpos
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", probs, vv.astype(jnp.float32))
    h = h + (o.swapaxes(1, 2).reshape(b, s, H).astype(h.dtype) @ lp["wo"])

    xf = h.astype(jnp.float32)
    x = (xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + c.rms_norm_eps)
         * lp["ln2"]).astype(h.dtype)
    h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    return h, cache_layer


def forward_with_cache(params, input_ids, cache, index, config: LlamaConfig):
    """→ (logits_last (B, V), new_cache). index: current write offset."""
    c = config
    max_len = cache.shape[-2]
    rope_full = rope_cos_sin(max_len, c.hidden_size // c.num_attention_heads,
                             c.rope_theta, jnp.float32)
    h = jnp.take(params["embed"], input_ids, axis=0)

    def body(carry, xs):
        hh = carry
        lp, cache_layer = xs
        hh, new_cl = _layer_decode(lp, hh, cache_layer, index, rope_full, c)
        return hh, new_cl

    h, new_cache = lax.scan(body, h, (params["layers"], cache))
    hf = h.astype(jnp.float32)
    h = (hf * lax.rsqrt(jnp.mean(hf * hf, -1, keepdims=True) + c.rms_norm_eps)
         * params["final_norm"]).astype(h.dtype)
    logits = h[:, -1, :] @ params["lm_head"]
    return logits.astype(jnp.float32), new_cache


def make_generate(config: LlamaConfig, max_len, max_new_tokens,
                  temperature=0.0, top_k=0):
    """Compiled greedy/sampled generation: prefill jit + decode-scan jit."""

    prefill = jax.jit(functools.partial(forward_with_cache, config=config),
                      static_argnames=())

    def decode_all(params, first_tok, cache, start_index, key):
        def step(carry, _):
            tok, cache, idx, key = carry
            logits, cache = forward_with_cache(params, tok[:, None], cache,
                                               idx, config)
            if temperature > 0.0:
                key, sub = jax.random.split(key)
                lg = logits / temperature
                if top_k:
                    kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
                    lg = jnp.where(lg < kth, -1e30, lg)
                nxt = jax.random.categorical(sub, lg, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return (nxt, cache, idx + 1, key), nxt

        (_, cache, _, _), toks = lax.scan(
            step, (first_tok, cache, start_index, key),
            None, length=max_new_tokens - 1)
        return jnp.concatenate([first_tok[:, None], toks.T], axis=1)

    decode_jit = jax.jit(decode_all)

    def generate(params, prompt_ids, seed=0):
        b, plen = prompt_ids.shape
        cache = init_cache(config, b, max_len,
                           params["embed"].dtype)
        logits, cache = prefill(params, prompt_ids, cache, 0)
        first = jnp.argmax(logits, axis=-1) if temperature == 0.0 else \
            jax.random.categorical(jax.random.key(seed), logits / temperature,
                                   axis=-1)
        out = decode_jit(params, first, cache, jnp.asarray(plen),
                         jax.random.key(seed + 1))
        return jnp.concatenate([prompt_ids, out], axis=1)

    return generate
